"""Incident flight recorder + resource accounting + on-demand profiling
(PR 15).

Covers the forensics tentpole end to end: the bounded typed-event ring
every subsystem records into, the event-spool contract riding PR 13's
rotation/clock normalization (one merged timeline with trace spans),
`manager incident` capture/list/show bundles, the ResourceLedger HBM
decomposition (weights via PR 14 stored-dtype bytes, KV/state lanes via
PR 12 bucket geometry, AOT executables via PR 11 stats), per-process
resource gauges, and POST /debug/profile.  The real-process acceptance
(SIGKILL a replica -> supervisor auto-captures a bundle whose merged
timeline covers the kill) runs the production manager path and is
`slow`-marked like the PR 10 chaos A/B.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from analytics_zoo_tpu.common.observability import (FlightRecorder,
                                                    get_recorder,
                                                    process_stats)
from analytics_zoo_tpu.inference.inference_model import InferenceModel
from analytics_zoo_tpu.inference.resources import ResourceLedger
from analytics_zoo_tpu.nn import Sequential
from analytics_zoo_tpu.nn.layers import Dense
from analytics_zoo_tpu.serving import incident, tracecollect
from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
from analytics_zoo_tpu.serving.engine import ClusterServing, ServingParams
from analytics_zoo_tpu.serving.queues import InProcQueue

pytestmark = pytest.mark.forensics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _model(din=16, dout=8):
    m = Sequential()
    m.add(Dense(dout, activation="softmax", input_shape=(din,),
                name=f"fx{din}x{dout}"))
    m.init_weights()
    im = InferenceModel()
    im.do_load_model(m)
    return im


def _http_json(url, data=None, headers=None, timeout=10, method=None):
    req = urllib.request.Request(url, data=data, headers=headers or {},
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# -- flight recorder ------------------------------------------------------------

def test_recorder_ring_bounds_and_drain():
    r = FlightRecorder(maxlen=32, replica_id="rX")
    for i in range(50):
        r.record("tick", i=i)
    st = r.stats()
    assert st["buffered"] == 32 and st["recorded"] == 50
    assert st["dropped"] == 50 - 32          # eviction is observable
    evs = r.events("tick")
    assert [e["i"] for e in evs] == list(range(18, 50))   # newest kept
    assert all(e["replica_id"] == "rX" and e["ts"] > 0 for e in evs)
    drained = r.drain_events()
    assert len(drained) == 32
    assert r.events() == [] and r.drain_events() == []    # atomic clear
    # resize keeps the most recent events
    for i in range(10):
        r.record("t2", i=i)
    r.resize(64)
    assert r.maxlen == 64 and len(r.events()) == 10


def test_recorder_is_process_wide_and_none_attrs_dropped():
    r = get_recorder()
    assert get_recorder() is r
    ev = r.record("probe", a=1, b=None)
    assert "b" not in ev and ev["a"] == 1 and ev["event"] == "probe"


def test_process_stats_fields():
    st = process_stats()
    assert st["rss_bytes"] and st["rss_bytes"] > 1 << 20
    assert st["cpu_seconds"] is not None and st["cpu_seconds"] >= 0
    assert st["open_fds"] and st["open_fds"] >= 3
    assert st["threads"] and st["threads"] >= 1


# -- event spool contract (satellite: merge_spools accepts event spools) --------

def test_event_spool_merges_onto_span_timeline(tmp_path):
    """Events and spans from different monotonic epochs land ordered on
    ONE wall timeline via their drain-time clock records; events keep
    kind="event" and mirror their name into `stage`."""
    base = str(tmp_path / "p.pid")
    wall = 5_000_000.0
    # span spool, process A with epoch ~100
    with open(tracecollect.spool_path(base + ".r0"), "w") as f:
        f.write(json.dumps({"kind": "clock", "wall": wall,
                            "mono": 100.0}) + "\n")
        f.write(json.dumps({"kind": "span", "trace_id": "t1", "uri": "u",
                            "stage": "predict", "ts": 101.0,
                            "dur_s": 0.5, "replica_id": "r0"}) + "\n")
    # event spool, supervisor with a wildly different epoch ~90000
    with open(tracecollect.events_path(base), "w") as f:
        f.write(json.dumps({"kind": "clock", "wall": wall,
                            "mono": 90000.0}) + "\n")
        f.write(json.dumps({"kind": "event", "event": "replica_exit",
                            "ts": 90002.0, "index": 1,
                            "replica_id": "supervisor"}) + "\n")
    merged = tracecollect.collect(base, events=True)
    assert [s.get("stage") for s in merged] == ["predict", "replica_exit"]
    assert abs(merged[0]["ts_wall"] - (wall + 1.0)) < 1e-6
    assert abs(merged[1]["ts_wall"] - (wall + 2.0)) < 1e-6
    assert merged[1]["kind"] == "event" and merged[1]["index"] == 1
    # span-only collect (manager trace) stays event-free
    spans_only = tracecollect.collect(base)
    assert [s.get("stage") for s in spans_only] == ["predict"]


def test_append_events_rotation(tmp_path):
    path = str(tmp_path / "e.events.jsonl")
    big = [{"event": "x", "ts": float(i), "pad": "y" * 100}
           for i in range(50)]
    tracecollect.append_events(path, big, source="r0", max_bytes=1000)
    tracecollect.append_events(path, big, source="r0", max_bytes=1000)
    assert os.path.exists(path + ".1")       # one-generation rotation
    assert len(tracecollect.find_event_spools(str(tmp_path / "e"))) == 2


# -- engine event instrumentation -----------------------------------------------

def test_engine_records_lifecycle_events():
    im = _model()
    q = InProcQueue()
    s = ClusterServing(im, q, params=ServingParams(batch_size=4,
                                                   recorder_ring=8192))
    s.recorder.clear()
    cin, cout = InputQueue(q), OutputQueue(q)
    uris = [cin.enqueue_tensor(f"u{i}",
                               np.random.rand(16).astype(np.float32))
            for i in range(6)]
    s.start()
    res = cout.query_many(uris, timeout_s=30)
    assert sum(1 for r in res.values() if r and "value" in r) == 6
    s.retune(max_batch=8)
    # a poisoned record quarantines AND records the event
    q.xadd({"uri": "poison", "b64": "!!!notbase64!!!"})
    deadline = time.monotonic() + 10
    while s.dead_lettered < 1 and time.monotonic() < deadline:
        time.sleep(0.05)
    s.shutdown(drain_s=2.0)
    kinds = [e["event"] for e in s.recorder.events()]
    assert "start" in kinds and "shutdown" in kinds
    assert "retune" in kinds and "quarantine" in kinds
    quar = s.recorder.events("quarantine")[0]
    assert quar["rid"] == "poison" and quar["replica"] == s.replica_id
    # health carries ring pressure
    assert s.health()["recorder"]["recorded"] >= len(kinds)


def test_engine_recorder_off_is_noop():
    im = _model()
    s = ClusterServing(im, InProcQueue(),
                       params=ServingParams(flight_recorder=False))
    s.recorder.clear()
    s.start()
    s.shutdown()
    assert s.recorder.events() == []


# -- resource ledger ------------------------------------------------------------

def test_ledger_weights_match_quantize_accounting():
    from analytics_zoo_tpu.inference.quantize import weight_bytes
    im = _model(din=64, dout=32)
    ledger = ResourceLedger(im)
    assert ledger.weights_bytes() == weight_bytes(im._params) \
        + weight_bytes(im._state or {})
    doc = ledger.doc()
    assert doc["weights_bytes"] > 0
    assert doc["kv_state_bytes"] == 0        # no generation lanes
    assert doc["quantized_bits"] == 0
    assert doc["total_bytes"] >= doc["weights_bytes"]


def test_int4_weights_component_reads_8x_below_float():
    """ISSUE 15 acceptance: the HBM decomposition's weights component for
    an int4-quantized model reads ~8x below its float twin."""
    im_f = _model(din=1024, dout=256)
    im_q = _model(din=1024, dout=256)
    im_q.do_quantize(None, force=True, bits=4, group_size=128)
    wf = ResourceLedger(im_f).weights_bytes()
    wq = ResourceLedger(im_q).weights_bytes()
    ratio = wf / wq
    assert 6.5 <= ratio <= 9.0, (wf, wq, ratio)
    doc = ResourceLedger(im_q).doc()
    assert doc["quantized_bits"] == 4


def test_per_program_exec_counters_keyed_by_manifest_entry():
    im = _model()
    x = np.random.rand(3, 16).astype(np.float32)
    im.do_predict(x)
    im.do_predict(x)
    im.do_predict(np.random.rand(7, 16).astype(np.float32))
    progs = im.aot_stats()["programs"]
    # pow-2 bucket labels, manifest-style: b4 twice, b8 once
    assert progs.get("b4x16/<f4") == 2, progs
    assert progs.get("b8x16/<f4") == 1, progs
    ledger = ResourceLedger(im)
    exes = ledger.executables()
    assert exes["count"] == 2 and exes["programs"] == progs


def test_health_doc_resources_and_prom_gauges():
    im = _model()
    q = InProcQueue()
    s = ClusterServing(im, q, params=ServingParams(batch_size=4))
    cin, cout = InputQueue(q), OutputQueue(q)
    uris = [cin.enqueue_tensor(f"u{i}",
                               np.random.rand(16).astype(np.float32))
            for i in range(4)]
    s.start()
    assert all(r and "value" in r
               for r in cout.query_many(uris, timeout_s=30).values())
    h = s.health()
    res = h["resources"]
    assert res["weights_bytes"] > 0
    assert res["executables"]["count"] >= 1
    assert sum(res["executables"]["programs"].values()) >= 1
    assert h["process"]["rss_bytes"] > 0
    prom = s.prom_metrics()
    s.shutdown()
    assert 'serving_hbm_bytes{component="weights"}' in prom
    assert 'serving_hbm_bytes{component="kv_state"}' in prom
    assert 'serving_hbm_bytes{component="executables"}' in prom
    for name in ("process_resident_memory_bytes",
                 "process_cpu_seconds_total", "process_open_fds",
                 "process_threads_total"):
        assert name in prom


@pytest.mark.generation
def test_generation_kv_state_bytes():
    import jax
    from analytics_zoo_tpu.models.textmodels import TransformerLM
    from analytics_zoo_tpu.serving.generate import (ContinuousBatcher,
                                                    GenerationParams)
    lm = TransformerLM(vocab_size=64, hidden=32, n_head=2, n_layers=1,
                       max_len=64)
    im = InferenceModel().do_load_model(
        lm, lm.build(jax.random.PRNGKey(0)), {})
    gen = GenerationParams(max_active_slots=4, max_tokens=8,
                           max_prompt_len=16, bucket_lens=[32])
    b = ContinuousBatcher(im, gen)
    expect = 0
    for lane in b._lanes:
        expect += sum(
            int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
            for leaf in jax.tree_util.tree_leaves(lane.state))
        expect += lane.tokens.nbytes
    assert b.state_bytes() == expect and expect > 0
    ledger = ResourceLedger(im, batcher=b)
    assert ledger.kv_state_bytes() == expect
    assert ledger.doc()["kv_state_bytes"] == expect
    # scheduler program exec counters join the ledger's program map
    from analytics_zoo_tpu.serving.generate import GenRequest
    assert b.submit(GenRequest("g1", np.arange(1, 5, dtype=np.int32)))
    steps = 0
    while not b.idle and steps < 50:
        b.step()
        steps += 1
    progs = b.program_stats()["programs"]
    assert any(k.startswith("prefill:") for k in progs), progs
    assert any(k.startswith("insert:") for k in progs), progs
    assert any(k.startswith("decode_step@") for k in progs), progs
    assert b.program_stats()["count"] >= 3
    merged = ledger.doc()["executables"]["programs"]
    assert all(k in merged for k in progs)


# -- fleet aggregation ----------------------------------------------------------

def test_fleet_aggregates_resources_and_process():
    from analytics_zoo_tpu.serving import fleet
    docs = {}
    for i in range(2):
        docs[i] = {
            "total_records": 5, "running": True, "replica_id": f"r{i}",
            "stages": {"e2e": {"p99_ms": 10.0, "p50_ms": 5.0}},
            "workers": {}, "queue": {"depth": 1},
            "resources": {"weights_bytes": 1000, "kv_state_bytes": 200,
                          "executables": {"count": 3, "code_bytes": 50},
                          "total_bytes": 1250},
            "process": {"rss_bytes": (i + 1) * 1000, "cpu_seconds": 1.5,
                        "open_fds": 10, "threads": 4},
        }
    agg = fleet.aggregate_health(docs)
    assert agg["resources"] == {
        "weights_bytes": 2000, "kv_state_bytes": 400, "executables": 6,
        "executable_code_bytes": 100, "total_bytes": 2500}
    assert agg["process"]["rss_bytes"] == 3000
    assert agg["process"]["rss_max_bytes"] == 2000
    assert agg["process"]["cpu_seconds"] == 3.0
    assert agg["process"]["open_fds"] == 20
    doc = fleet.fleet_metrics(docs)
    assert doc["resources"]["weights_bytes"] == 2000
    assert doc["process"]["threads"] == 8
    assert doc["per_replica"]["r0"]["rss_bytes"] == 1000
    assert doc["per_replica"]["r1"]["hbm_bytes"] == 1250
    # docs without the new blocks (rolling upgrade) aggregate to None
    old = {0: {k: v for k, v in docs[0].items()
               if k not in ("resources", "process")}}
    agg2 = fleet.aggregate_health(old)
    assert agg2["resources"] is None and agg2["process"] is None


# -- on-demand profiling --------------------------------------------------------

def test_profile_endpoint_and_gating(tmp_path):
    im = _model()
    s = ClusterServing(im, InProcQueue(),
                       params=ServingParams(http_port=0))
    s.profile_dir = str(tmp_path / "profiles")
    s.start()
    try:
        url = f"http://127.0.0.1:{s._http.port}/debug/profile"
        code, doc = _http_json(url + "?seconds=0.3", data=b"",
                               method="POST")
        assert code == 202, doc
        assert doc["profiling"] and doc["path"].startswith(
            s.profile_dir)
        assert os.path.isdir(doc["path"])
        # second trace while one runs -> 409
        code2, doc2 = _http_json(url + "?seconds=0.3", data=b"",
                                 method="POST")
        assert code2 == 409, doc2
        # events mark the trace on the forensic timeline
        assert s.recorder.events("profile_start")
        # arming is async (start_trace can take ~15s bringing the
        # profiler server up in sandboxed containers): wait out the full
        # cycle, then the trace must have written xplane files
        deadline = time.monotonic() + 90
        while s._profile_active and time.monotonic() < deadline:
            time.sleep(0.1)
        assert not s._profile_active
        assert s.recorder.events("profile_done"), \
            s.recorder.events("profile_error")
        assert any(files for _, _, files in os.walk(doc["path"]))
        # bad seconds -> 400
        code3, _ = _http_json(url + "?seconds=0", data=b"",
                              method="POST")
        assert code3 == 400
        # `manager profile` CLI: POSTs the same endpoint off the config's
        # probe port (replica index 0 -> http_port + 0)
        cfg = tmp_path / "config.yaml"
        cfg.write_text("params:\n"
                       f"  http_port: {s._http.port}\n")
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "-m", "analytics_zoo_tpu.serving.manager",
             "profile", "0", "-c", str(cfg), "--seconds", "0.2"],
            env=env, capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        cli = json.loads(out.stdout)
        assert cli["profiling"] and cli["path"].startswith(s.profile_dir)
    finally:
        s.shutdown()


def test_profile_gated_off():
    im = _model()
    s = ClusterServing(im, InProcQueue(),
                       params=ServingParams(http_port=0,
                                            profiling=False))
    s.start()
    try:
        code, doc = _http_json(
            f"http://127.0.0.1:{s._http.port}/debug/profile?seconds=1",
            data=b"", method="POST")
        assert code == 404 and "disabled" in doc["error"]
    finally:
        s.shutdown()


# -- incident bundles -----------------------------------------------------------

def _fake_deployment(base):
    tracecollect.append_spans(
        tracecollect.spool_path(base + ".r0"),
        [{"trace_id": "t1", "uri": "u1", "stage": "predict", "ts": 1.0,
          "dur_s": 0.01}], source="replica-0")
    tracecollect.append_events(
        tracecollect.events_path(base + ".r0"),
        [{"event": "start", "ts": 0.5},
         {"event": "quarantine", "ts": 1.1, "rid": "u9",
          "error": "poison"}], source="replica-0")
    tracecollect.append_events(
        tracecollect.events_path(base),
        [{"event": "replica_exit", "ts": 1.2, "index": 0}],
        source="supervisor")
    with open(base + ".r0.health.json", "w") as f:
        json.dump({"replica_id": "replica-0", "running": True,
                   "clock": {"wall": 100.0, "monotonic": 1.0}}, f)
    with open(base + ".replicas", "w") as f:
        f.write("2")
    with open(base + ".knobs.json", "w") as f:
        json.dump({"max_batch": 8}, f)


def test_incident_capture_list_render(tmp_path):
    base = str(tmp_path / "cs.pid")
    _fake_deployment(base)
    bundle = incident.capture(base, "unit-test", meta={"k": 1})
    assert bundle and os.path.isdir(bundle)
    names = set(os.listdir(bundle))
    assert "incident.json" in names
    assert "cs.pid.r0.spans.jsonl" in names
    assert "cs.pid.r0.events.jsonl" in names
    assert "cs.pid.events.jsonl" in names
    assert "cs.pid.r0.health.json" in names
    assert "cs.pid.replicas" in names and "cs.pid.knobs.json" in names
    lst = incident.list_incidents(base)
    assert len(lst) == 1 and lst[0]["reason"] == "unit-test"
    assert lst[0]["meta"] == {"k": 1}
    doc = incident.render(bundle)
    whats = [e["what"] for e in doc["timeline"]]
    # events + spans, clock-normalized into one order
    assert whats == ["start", "predict", "quarantine", "replica_exit"]
    kinds = [e["kind"] for e in doc["timeline"]]
    assert kinds == ["event", "span", "event", "event"]
    assert doc["errors"] == ["poison"]
    assert {"replica-0", "supervisor"} <= set(doc["processes"])
    assert doc["events_by_kind"]["quarantine"] == 1


def test_incident_empty_and_eviction(tmp_path):
    base = str(tmp_path / "cs.pid")
    assert incident.capture(base, "nothing") is None
    _fake_deployment(base)
    bundles = [incident.capture(base, f"r{i}", max_bundles=3)
               for i in range(5)]
    assert all(bundles)
    left = incident.list_incidents(base)
    assert len(left) == 3                     # oldest evicted
    assert [b["reason"] for b in left] == ["r2", "r3", "r4"]
    # resolve: latest by default, by name, unknown -> None
    assert incident.resolve_bundle(base) == left[-1]["path"]
    assert incident.resolve_bundle(base, left[0]["bundle"]) \
        == left[0]["path"]
    assert incident.resolve_bundle(base, "nope") is None


def test_incident_cli_and_viewer(tmp_path):
    base = str(tmp_path / "cs.pid")
    _fake_deployment(base)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    run = [sys.executable, "-m", "analytics_zoo_tpu.serving.manager"]
    out = subprocess.run(run + ["incident", "--pidfile", base],
                         env=env, capture_output=True, text=True,
                         timeout=60)
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout)["captured"] is True
    out = subprocess.run(run + ["incident", "--list", "--pidfile", base],
                         env=env, capture_output=True, text=True,
                         timeout=60)
    lst = json.loads(out.stdout)["incidents"]
    assert len(lst) == 1 and lst[0]["reason"] == "operator"
    out = subprocess.run(run + ["incident", "--show", "--pidfile", base],
                         env=env, capture_output=True, text=True,
                         timeout=60)
    doc = json.loads(out.stdout)
    assert doc["reason"] == "operator"
    assert [e["what"] for e in doc["timeline"]] \
        == ["start", "predict", "quarantine", "replica_exit"]
    # the standalone viewer renders the same bundle as text
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "incident_view.py"),
         "--pidfile", base], env=env, capture_output=True, text=True,
        timeout=60)
    assert out.returncode == 0, out.stderr
    assert "replica_exit" in out.stdout and "quarantine" in out.stdout
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "incident_view.py"),
         "--smoke"], env=env, capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "ALL OK" in out.stdout


# -- real-process acceptance ----------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_sigkill_replica_auto_captures_incident(tmp_path):
    """ISSUE 15 acceptance: `manager start --replicas 2`, SIGKILL one
    replica -> the supervisor auto-captures an incident bundle;
    `manager incident --show` renders a merged cross-process timeline
    (recorder events + trace spans) covering the kill; /healthz carries
    the `resources` HBM decomposition."""
    din = 8
    topo = tmp_path / "topology.py"
    topo.write_text(
        "from analytics_zoo_tpu.nn import Sequential\n"
        "from analytics_zoo_tpu.nn.layers import Dense\n"
        "def build_model():\n"
        "    m = Sequential()\n"
        f"    m.add(Dense(4, activation='softmax', input_shape=({din},),"
        " name='e2efc'))\n"
        "    return m\n")
    from analytics_zoo_tpu.nn import Sequential as _Seq
    from analytics_zoo_tpu.nn.layers import Dense as _Dense
    m = _Seq()
    m.add(_Dense(4, activation="softmax", input_shape=(din,),
                 name="e2efc"))
    m.init_weights()
    weights = tmp_path / "weights.npz"
    m.save_weights(str(weights))
    qdir = tmp_path / "q"
    port = _free_port()
    cfg = tmp_path / "config.yaml"
    cfg.write_text(
        "model:\n"
        f"  path: {weights}\n"
        "  type: zoo\n"
        f"  topology: {topo}\n"
        "data:\n"
        f"  src: file:{qdir}\n"
        "params:\n"
        "  batch_size: 4\n"
        f"  http_port: {port}\n"
        "  drain_s: 2\n"
        "  lease_s: 2\n"
        "  reclaim_interval_s: 0.5\n"
        "  compile_cache_dir: off\n"
        "incident:\n"
        "  on_crash: true\n"
        "  cooldown_s: 1\n")
    base = str(tmp_path / "cs.pid")
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "analytics_zoo_tpu.serving.manager",
         "start", "-c", str(cfg), "--pidfile", base, "--replicas", "2",
         "--foreground", "--no-prewarm"],
        env=env, cwd=str(tmp_path), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        # both replicas ready
        deadline = time.monotonic() + 120
        ready = set()
        while len(ready) < 2 and time.monotonic() < deadline:
            assert proc.poll() is None, proc.stderr.read()[-3000:]
            for i in range(2):
                if i in ready:
                    continue
                try:
                    code, _ = _http_json(
                        f"http://127.0.0.1:{port + i}/readyz", timeout=2)
                    if code == 200:
                        ready.add(i)
                except Exception:  # noqa: BLE001 — still booting
                    pass
            time.sleep(0.3)
        assert ready == {0, 1}, f"replicas not ready: {ready}"
        # traffic through replica 0's gateway, then a health scrape with
        # the resources block
        body = json.dumps({"uri": "acc-1",
                           "data": [0.1] * din}).encode()
        code, ack = _http_json(
            f"http://127.0.0.1:{port}/v1/enqueue", data=body,
            headers={"Content-Type": "application/json"})
        assert code == 200, ack
        code, res = _http_json(
            f"http://127.0.0.1:{port}/v1/result/acc-1?timeout_s=30",
            timeout=40)
        assert code == 200 and "value" in res, res
        code, h = _http_json(f"http://127.0.0.1:{port}/healthz")
        assert code == 200
        assert h["resources"]["weights_bytes"] > 0
        assert h["resources"]["executables"]["count"] >= 1
        assert h["process"]["rss_bytes"] > 0
        # SIGKILL replica 1 -> supervisor reaps, respawns, auto-captures
        with open(base + ".r1") as f:
            victim = int(f.read().strip())
        os.kill(victim, signal.SIGKILL)
        inc_dir = base + ".incidents"
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if os.path.isdir(inc_dir) and os.listdir(inc_dir):
                break
            time.sleep(0.3)
        assert os.path.isdir(inc_dir) and os.listdir(inc_dir), \
            "supervisor captured no incident bundle"
        lst = incident.list_incidents(base)
        assert any("replica-1-crash" in str(b.get("reason"))
                   for b in lst), lst
        # the CLI renders a merged cross-process timeline covering the
        # kill: supervisor lifecycle events + replica events + spans
        out = subprocess.run(
            [sys.executable, "-m", "analytics_zoo_tpu.serving.manager",
             "incident", "--show", "--pidfile", base, "--last", "500"],
            env=env, cwd=str(tmp_path), capture_output=True, text=True,
            timeout=60)
        assert out.returncode == 0, out.stderr
        doc = json.loads(out.stdout)
        assert doc["reason"].startswith("replica-1-crash")
        whats = {e["what"] for e in doc["timeline"]}
        kinds = {e["kind"] for e in doc["timeline"]}
        assert kinds == {"event", "span"}, kinds
        assert "replica_exit" in whats          # the kill itself
        assert "start" in whats                 # replica lifecycle
        assert whats & {"predict", "read", "gateway", "write"}, whats
        procs = set(doc["processes"])
        assert "supervisor" in procs
        assert any(p.startswith("replica-") for p in procs)
        # respawn: r1 comes back with a fresh pid
        deadline = time.monotonic() + 60
        respawned = None
        while time.monotonic() < deadline:
            try:
                with open(base + ".r1") as f:
                    p2 = int(f.read().strip())
                if p2 != victim:
                    respawned = p2
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.3)
        assert respawned, "replica 1 never respawned"
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
