"""Model-zoo wave 1: TextClassifier, AnomalyDetector, WideAndDeep, Seq2seq, KNRM,
SessionRecommender — build, train a little, check learning + API contracts."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.models import (
    AnomalyDetector, KNRM, Seq2seq, TextClassifier)
from analytics_zoo_tpu.models.recommendation import (
    ColumnFeatureInfo, SessionRecommender, WideAndDeep)
from analytics_zoo_tpu.nn.optimizers import Adam


def test_text_classifier_cnn_learns(ctx):
    """Class = which half of the vocab dominates the sequence."""
    g = np.random.default_rng(0)
    n, T, V = 512, 20, 40
    y = g.integers(0, 2, n)
    x = np.where(y[:, None] == 0,
                 g.integers(1, V // 2, (n, T)),
                 g.integers(V // 2, V, (n, T))).astype(np.float32)
    tc = TextClassifier(class_num=2, vocab_size=V, embedding_dim=16,
                        sequence_length=T, encoder="cnn", encoder_output_dim=32)
    tc.compile(optimizer=Adam(lr=0.01),
               loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    hist = tc.fit(x, y[:, None].astype(np.float32), batch_size=64, nb_epoch=4,
                  verbose=False)
    res = tc.evaluate(x, y[:, None].astype(np.float32), batch_size=64)
    assert res["accuracy"] > 0.95


@pytest.mark.parametrize("encoder", ["lstm", "gru"])
def test_text_classifier_rnn_builds(ctx, encoder):
    tc = TextClassifier(class_num=3, vocab_size=30, embedding_dim=8,
                        sequence_length=12, encoder=encoder,
                        encoder_output_dim=16)
    tc.init_weights()
    x = np.ones((4, 12), np.float32)
    assert tc.predict(x, batch_size=8).shape == (4, 3)


def test_anomaly_detector_pipeline(ctx):
    t = np.arange(0, 40, 0.1, dtype=np.float32)
    series = np.sin(t)
    x, y = AnomalyDetector.unroll(series, unroll_length=20)
    assert x.shape[1:] == (20, 1) and x.shape[0] == y.shape[0]
    ad = AnomalyDetector(feature_shape=(20, 1), hidden_layers=(8, 8),
                         dropouts=(0.0, 0.0))
    ad.compile(optimizer=Adam(lr=0.01), loss="mse")
    hist = ad.fit(x, y, batch_size=64, nb_epoch=5, verbose=False)
    assert hist.history["loss"][-1] < hist.history["loss"][0]
    pred = ad.predict(x, batch_size=64)
    idx, dist, thr = AnomalyDetector.detect_anomalies(y, pred,
                                                      anomaly_fraction=0.1)
    assert len(idx) >= int(0.1 * len(y) * 0.9)
    assert (dist[idx] >= thr).all()


def test_wide_and_deep_variants(ctx):
    info = ColumnFeatureInfo(
        wide_base_cols=["gender", "occ"], wide_base_dims=[3, 5],
        wide_cross_cols=["gender_age"], wide_cross_dims=[50],
        indicator_cols=["occ"], indicator_dims=[5],
        embed_cols=["user", "item"], embed_in_dims=[100, 80],
        embed_out_dims=[8, 8],
        continuous_cols=["age"])
    g = np.random.default_rng(1)
    B = 256
    cols = {"gender": g.integers(0, 3, B), "age": g.normal(40, 10, B),
            "occ": g.integers(0, 5, B), "user": g.integers(1, 100, B),
            "item": g.integers(1, 80, B),
            "gender_age": None}  # cross computed from parts
    # label correlated with occ
    y = (np.asarray(cols["occ"]) % 2).astype(np.float32)[:, None]

    for mt in ["wide", "deep", "wide_n_deep"]:
        wad = WideAndDeep(class_num=2, column_info=info, model_type=mt)
        x = wad.to_model_inputs(cols)
        wad.compile(optimizer=Adam(lr=0.01),
                    loss="sparse_categorical_crossentropy",
                    metrics=["accuracy"])
        wad.fit(x, y, batch_size=64, nb_epoch=10, verbose=False)
        res = wad.evaluate(x, y, batch_size=64)
        assert res["accuracy"] > 0.9, mt


def test_seq2seq_copy_task(ctx):
    """Seq2seq learns to copy short sequences (teacher forcing)."""
    g = np.random.default_rng(2)
    V, T, n = 12, 5, 512
    src = g.integers(2, V, (n, T)).astype(np.float32)
    dec_in = np.concatenate([np.ones((n, 1)), src[:, :-1]], axis=1)  # <s>=1
    target = src.copy()
    s2s = Seq2seq(vocab_size=V, embed_dim=24, hidden_sizes=(64,))
    s2s.compile(optimizer=Adam(lr=0.01),
                loss="sparse_categorical_crossentropy")
    hist = s2s.fit([src, dec_in], target[..., None], batch_size=64,
                   nb_epoch=12, verbose=False)
    assert hist.history["loss"][-1] < 0.5 * hist.history["loss"][0]
    # greedy inference emits valid tokens
    toks = s2s.infer(s2s.get_weights(), src[:4], start_sign=1, max_seq_len=T)
    assert toks.shape == (4, T)
    assert (toks >= 0).all() and (toks < V).all()


def test_knrm_ranking(ctx):
    """Relevant docs share tokens with the query; KNRM must rank them higher."""
    g = np.random.default_rng(3)
    V, Tq, Td, n = 50, 4, 8, 384
    q = g.integers(1, V, (n, Tq))
    rel = g.integers(0, 2, n)
    # relevant doc contains the query tokens; irrelevant is random
    d = np.where(rel[:, None] == 1,
                 np.concatenate([q, g.integers(1, V, (n, Td - Tq))], axis=1),
                 g.integers(1, V, (n, Td)))
    knrm = KNRM(text1_length=Tq, text2_length=Td, vocab_size=V, embed_size=16,
                kernel_num=11)
    knrm.compile(optimizer=Adam(lr=0.01), loss="binary_crossentropy",
                 metrics=["auc"])
    knrm.fit([q.astype(np.float32), d.astype(np.float32)],
             rel.astype(np.float32)[:, None], batch_size=64, nb_epoch=6,
             verbose=False)
    res = knrm.evaluate([q.astype(np.float32), d.astype(np.float32)],
                        rel.astype(np.float32)[:, None], batch_size=64)
    assert res["auc"] > 0.8


def test_session_recommender(ctx):
    """Next item = last item + 1 (mod V) — GRU should learn the pattern."""
    g = np.random.default_rng(4)
    V, L, n = 30, 6, 512
    start = g.integers(1, V - L - 1, n)
    sessions = (start[:, None] + np.arange(L)[None, :]).astype(np.float32)
    nxt = (start + L).astype(np.float32)[:, None]
    sr = SessionRecommender(item_count=V, item_embed=16,
                            rnn_hidden_layers=(32,), session_length=L)
    sr.compile(optimizer=Adam(lr=0.01),
               loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    hist = sr.fit(sessions, nxt, batch_size=64, nb_epoch=8, verbose=False)
    res = sr.evaluate(sessions, nxt, batch_size=64)
    assert res["accuracy"] > 0.8
    recs = sr.recommend_for_session(sessions[:3], max_items=4)
    assert len(recs) == 3 and len(recs[0]) == 4


def test_resnet_cifar_trains(ctx):
    """Tiny ResNet-18 (cifar stem) on synthetic 16x16 two-class data."""
    from analytics_zoo_tpu.models.imageclassification import resnet
    g = np.random.default_rng(5)
    n = 128
    y = g.integers(0, 2, n)
    # class 0: dark images, class 1: bright images
    x = np.where(y[:, None, None, None] == 0,
                 g.normal(-1.0, 0.5, (n, 16, 16, 3)),
                 g.normal(1.0, 0.5, (n, 16, 16, 3))).astype(np.float32)
    model = resnet(18, num_classes=2, input_shape=(16, 16, 3), stem="cifar")
    model.compile(optimizer=Adam(lr=0.01),
                  loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    hist = model.fit(x, y[:, None].astype(np.float32), batch_size=32, nb_epoch=3,
                     verbose=False)
    assert hist.history["loss"][-1] < 0.5 * hist.history["loss"][0]
    # BN moving stats are still cold after 12 steps (momentum .99), so judge the
    # classifier with batch statistics (training-mode forward)
    import jax
    probs = np.asarray(model.call(model.get_weights(), jnp.asarray(x),
                                  training=True))
    acc = (probs.argmax(-1) == y).mean()
    assert acc > 0.9, acc


def test_resnet50_builds_with_correct_params(ctx):
    from analytics_zoo_tpu.models.imageclassification import resnet
    model = resnet(50, num_classes=1000, input_shape=(32, 32, 3))
    n_params = model.param_count()
    # ResNet-50 ~25.5M params (conv + fc + bn gamma/beta)
    assert 24_000_000 < n_params < 27_000_000, n_params


def test_image_classifier_facade(ctx):
    from analytics_zoo_tpu.feature.image import ImageSet
    from analytics_zoo_tpu.models.imageclassification import ImageClassifier
    g = np.random.default_rng(6)
    clf = ImageClassifier("resnet18", num_classes=4, input_shape=(24, 24, 3),
                          stem="cifar")
    clf.init_weights()
    imgs = [g.integers(0, 255, (40, 40, 3)).astype(np.uint8) for _ in range(3)]
    iset = ImageSet.from_arrays(imgs)
    from analytics_zoo_tpu.feature.image import (ImageCenterCrop,
                                                 ImageChannelNormalize,
                                                 ImageResize)
    clf.preprocessor = (ImageResize(28, 28) >> ImageCenterCrop(24, 24)
                        >> ImageChannelNormalize(120, 120, 120, 60, 60, 60))
    idx, probs = clf.predict_image_set(iset, batch_size=8, top_k=2)
    assert idx.shape == (3, 2)
    assert (probs[:, 0] >= probs[:, 1]).all()


def test_seq2seq_bridge_family(ctx):
    """Bridge.scala:1-156 parity: passthrough / dense / densenonlinear /
    customized adapters between encoder and decoder states."""
    import jax
    import jax.numpy as jnp

    g = np.random.default_rng(4)
    V, B, T = 12, 6, 5
    enc = g.integers(0, V, (B, T)).astype(np.float32)
    dec = g.integers(0, V, (B, T)).astype(np.float32)

    outs = {}
    for bridge in ("passthrough", "dense", "densenonlinear",
                   lambda flat: flat * 0.5):
        s2s = Seq2seq(vocab_size=V, embed_dim=8, hidden_sizes=(16, 8),
                      bridge=bridge)
        params = s2s.build(jax.random.PRNGKey(0))
        if isinstance(bridge, str) and bridge.startswith("dense"):
            # amplify so tanh leaves its linear regime (tanh(x) ~= x at
            # glorot scale would make dense == densenonlinear numerically)
            params["bridge"]["W"] = params["bridge"]["W"] * 6.0
        y = s2s.call(params, [jnp.asarray(enc), jnp.asarray(dec)],
                     training=False)
        assert y.shape == (B, T, V)
        key = bridge if isinstance(bridge, str) else "customized"
        outs[key] = np.asarray(y)
        if bridge in ("dense", "densenonlinear"):
            S = sum(2 * h for h in (16, 8))
            assert params["bridge"]["W"].shape == (S, S)  # cross-layer mixing
    # the adapters genuinely change the decoder trajectory
    assert np.abs(outs["passthrough"] - outs["dense"]).max() > 1e-6
    assert np.abs(outs["dense"] - outs["densenonlinear"]).max() > 1e-6
    assert np.abs(outs["passthrough"] - outs["customized"]).max() > 1e-6


def test_seq2seq_rejects_unknown_bridge():
    import pytest as _pytest
    with _pytest.raises(ValueError, match="bridge"):
        Seq2seq(vocab_size=10, bridge="Dense")
