"""Published-weights ResNet import (round 5, VERDICT r4 next #2): a
torchvision-layout state_dict imports into the native `resnet()` graph and
matches the torch model's eval-mode forward to 1e-4 — torch-aligned padding
(padding="torch"), BN eps 1e-5, identity-shortcut fallback for basic blocks.

The torch reference below replicates torchvision's ResNet module naming
(conv1/bn1/layer{1..4}.{b}.conv{i}/downsample/fc) so its state_dict has the
exact published key schema.  Reference: ImageClassificationConfig.scala:1-190
(the registry whose names must resolve to the published architectures).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402

from analytics_zoo_tpu.models.imageclassification import (  # noqa: E402
    _RESNET_SPECS, ImageClassifier, load_torch_resnet, resnet)


class _BasicBlock(nn.Module):
    def __init__(self, cin, cout, stride=1):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(cout)
        self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        idn = x if self.downsample is None else self.downsample(x)
        h = torch.relu(self.bn1(self.conv1(x)))
        h = self.bn2(self.conv2(h))
        return torch.relu(h + idn)


class _Bottleneck(nn.Module):
    def __init__(self, cin, width, stride=1):
        super().__init__()
        cout = width * 4
        self.conv1 = nn.Conv2d(cin, width, 1, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(width)
        self.conv2 = nn.Conv2d(width, width, 3, stride, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(width)
        self.conv3 = nn.Conv2d(width, cout, 1, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        idn = x if self.downsample is None else self.downsample(x)
        h = torch.relu(self.bn1(self.conv1(x)))
        h = torch.relu(self.bn2(self.conv2(h)))
        h = self.bn3(self.conv3(h))
        return torch.relu(h + idn)


class _TorchResNet(nn.Module):
    """torchvision-named ResNet (conv1/bn1/layer1../fc)."""

    def __init__(self, kind, blocks, num_classes=10):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.maxpool = nn.MaxPool2d(3, 2, 1)
        cin, width = 64, 64
        for li, n in enumerate(blocks):
            mods = []
            for b in range(n):
                stride = 2 if (b == 0 and li > 0) else 1
                if kind == "bottleneck":
                    mods.append(_Bottleneck(cin, width, stride))
                    cin = width * 4
                else:
                    mods.append(_BasicBlock(cin, width, stride))
                    cin = width
            setattr(self, f"layer{li + 1}", nn.Sequential(*mods))
            width *= 2
        self.fc = nn.Linear(cin, num_classes)

    def forward(self, x):
        h = self.maxpool(torch.relu(self.bn1(self.conv1(x))))
        for li in range(1, 5):
            h = getattr(self, f"layer{li}")(h)
        h = h.mean(dim=(2, 3))
        return self.fc(h)


def _randomize_bn_stats(m, rng):
    for mod in m.modules():
        if isinstance(mod, nn.BatchNorm2d):
            mod.running_mean.copy_(torch.tensor(
                rng.normal(0, 0.5, mod.running_mean.shape), dtype=torch.float))
            mod.running_var.copy_(torch.tensor(
                rng.uniform(0.5, 2.0, mod.running_var.shape),
                dtype=torch.float))


@pytest.mark.parametrize("depth", [18, 50])
def test_torch_resnet_import_matches_eval_forward(rng, depth):
    kind, blocks = _RESNET_SPECS[depth]
    tm = _TorchResNet(kind, blocks, num_classes=10).eval()
    _randomize_bn_stats(tm, rng)
    sd = {k: v.detach().numpy() for k, v in tm.state_dict().items()}

    native = resnet(depth, num_classes=10, input_shape=(64, 64, 3),
                    padding="torch")
    load_torch_resnet(native, sd, name=f"resnet{depth}", blocks=blocks)

    x = rng.normal(size=(2, 64, 64, 3)).astype(np.float32)
    with torch.no_grad():
        logits = tm(torch.tensor(x.transpose(0, 3, 1, 2))).numpy()
    want = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    got = native.predict(x, batch_size=2)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_image_classifier_facade_torch_padding(rng):
    """ImageClassifier(padding='torch').load_torch_state_dict end to end."""
    kind, blocks = _RESNET_SPECS[18]
    tm = _TorchResNet(kind, blocks, num_classes=7).eval()
    _randomize_bn_stats(tm, rng)
    sd = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    clf = ImageClassifier("resnet18", num_classes=7,
                          input_shape=(64, 64, 3), padding="torch")
    clf.load_torch_state_dict(sd)
    x = rng.normal(size=(2, 64, 64, 3)).astype(np.float32)
    with torch.no_grad():
        logits = tm(torch.tensor(x.transpose(0, 3, 1, 2))).numpy()
    want = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    got = clf.predict(x, batch_size=2)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
