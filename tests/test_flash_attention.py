"""Pallas flash-attention kernel (interpret mode on CPU; compiled on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.ops.attention import _attention_xla
from analytics_zoo_tpu.ops.flash_attention import flash_attention


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_xla(causal):
    g = np.random.default_rng(0)
    B, H, T, D = 2, 2, 256, 64
    q, k, v = (jnp.asarray(g.normal(size=(B, H, T, D)), jnp.float32)
               for _ in range(3))
    out = flash_attention(q, k, v, causal, None, 64, 64, True)
    ref = _attention_xla(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_gradients_match():
    g = np.random.default_rng(1)
    B, H, T, D = 1, 2, 128, 32
    q, k, v = (jnp.asarray(g.normal(size=(B, H, T, D)), jnp.float32)
               for _ in range(3))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, None, 64, 64, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_attention_xla(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_pallas_backward_padded_T(causal):
    """The Pallas bwd kernels (round 5): non-block-multiple T exercises the
    padded-query/padded-key paths of both the dq and dkv kernels."""
    g = np.random.default_rng(3)
    B, H, T, D = 1, 2, 200, 32
    q, k, v = (jnp.asarray(g.normal(size=(B, H, T, D)), jnp.float32)
               for _ in range(3))
    ct = jnp.asarray(g.normal(size=(B, H, T, D)), jnp.float32)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, None, 64, 64, True)
                       * ct)

    def f_ref(q, k, v):
        return jnp.sum(_attention_xla(q, k, v, causal=causal) * ct)

    gf = jax.grad(f_flash, (0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


def test_flash_uneven_blocks():
    """T not divisible by default block: block sizes clamp to T."""
    g = np.random.default_rng(2)
    B, H, T, D = 1, 1, 64, 32
    q, k, v = (jnp.asarray(g.normal(size=(B, H, T, D)), jnp.float32)
               for _ in range(3))
    out = flash_attention(q, k, v, False, None, 128, 128, True)
    ref = _attention_xla(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_with_lse_matches_xla_forward_and_grad():
    """flash_attention_with_lse (round 5): both outputs match an XLA
    reference, INCLUDING gradients when the loss consumes the lse (its
    cotangent enters the backward as a delta shift)."""
    from analytics_zoo_tpu.ops.flash_attention import flash_attention_with_lse

    g = np.random.default_rng(5)
    B, H, T, D = 1, 2, 128, 32
    q, k, v = (jnp.asarray(g.normal(size=(B, H, T, D)), jnp.float32)
               for _ in range(3))
    ct_o = jnp.asarray(g.normal(size=(B, H, T, D)), jnp.float32)
    ct_l = jnp.asarray(g.normal(size=(B, H, T)), jnp.float32)
    scale = 1.0 / np.sqrt(D)

    def ref(q, k, v):
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        lse = jax.nn.logsumexp(logits, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd",
                         jax.nn.softmax(logits, -1), v)
        return out, lse

    def loss_flash(q, k, v):
        o, l = flash_attention_with_lse(q, k, v, False, None, 64, 64, True)
        return jnp.sum(o * ct_o) + jnp.sum(l * ct_l)

    def loss_ref(q, k, v):
        o, l = ref(q, k, v)
        return jnp.sum(o * ct_o) + jnp.sum(l * ct_l)

    of, lf = flash_attention_with_lse(q, k, v, False, None, 64, 64, True)
    orr, lr = ref(q, k, v)
    np.testing.assert_allclose(np.asarray(of), np.asarray(orr),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(lr),
                               rtol=2e-4, atol=2e-4)
    gf = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)
