"""Feature layer: preprocessing chains, image transforms, text pipeline, 3D ops."""

import numpy as np
import pytest

from analytics_zoo_tpu.feature.common import (
    ChainedPreprocessing, FeatureLabelPreprocessing, FnPreprocessing)
from analytics_zoo_tpu.feature.image import (
    ImageAspectScale, ImageBrightness, ImageCenterCrop, ImageChannelNormalize,
    ImageColorJitter, ImageExpand, ImageFeature, ImageHFlip, ImageRandomCrop,
    ImageRandomTransformer, ImageResize, ImageSet, ImageSetToSample, ImageVFlip)
from analytics_zoo_tpu.feature.image3d import (
    AffineTransform3D, CenterCrop3D, Crop3D, RandomCrop3D, Rotate3D)
from analytics_zoo_tpu.feature.text import (
    Relation, TextSet, generate_relation_lists, generate_relation_pairs,
    relation_pairs_to_arrays)


def _img(h=32, w=48):
    g = np.random.default_rng(0)
    return g.integers(0, 255, (h, w, 3)).astype(np.uint8)


def test_chain_composition():
    double = FnPreprocessing(lambda x: x * 2)
    inc = FnPreprocessing(lambda x: x + 1)
    chain = double >> inc >> double
    assert chain.transform(3) == 14
    assert isinstance(chain, ChainedPreprocessing)
    fl = FeatureLabelPreprocessing(double, inc)
    assert fl.transform((2, 5)) == (4, 6)


def test_image_resize_crop_flip():
    f = ImageFeature(image=_img())
    out = ImageResize(16, 24).transform(f)
    assert out.image.shape == (16, 24, 3)
    out = ImageCenterCrop(20, 20).transform(f)
    assert out.image.shape == (20, 20, 3)
    out = ImageRandomCrop(20, 20, seed=0).transform(f)
    assert out.image.shape == (20, 20, 3)
    flipped = ImageHFlip().transform(f)
    np.testing.assert_array_equal(flipped.image, f.image[:, ::-1])
    vflipped = ImageVFlip().transform(f)
    np.testing.assert_array_equal(vflipped.image, f.image[::-1])


def test_image_aspect_scale():
    f = ImageFeature(image=_img(100, 200))
    out = ImageAspectScale(50, max_size=120).transform(f)
    h, w = out.image.shape[:2]
    assert min(h, w) <= 50 and max(h, w) <= 120


def test_image_color_and_normalize():
    f = ImageFeature(image=_img())
    out = ImageBrightness(10, 10, seed=0).transform(f)
    assert (out.image >= f.image.astype(np.float32)).mean() > 0.9
    norm = ImageChannelNormalize(104, 117, 123, 2, 2, 2).transform(f)
    expect = (f.image.astype(np.float32)
              - np.asarray([104, 117, 123], np.float32)) / 2.0
    np.testing.assert_allclose(norm.image, expect)
    jit = ImageColorJitter(seed=1).transform(f)
    assert jit.image.shape == f.image.shape
    exp = ImageExpand(max_expand_ratio=2.0, seed=2).transform(f)
    assert exp.image.shape[0] >= f.image.shape[0]


def test_image_random_transformer_prob():
    f = ImageFeature(image=_img())
    never = ImageRandomTransformer(ImageHFlip(), p=0.0, seed=0)
    np.testing.assert_array_equal(never.transform(f).image, f.image)
    always = ImageRandomTransformer(ImageHFlip(), p=1.0, seed=0)
    np.testing.assert_array_equal(always.transform(f).image, f.image[:, ::-1])


def test_imageset_pipeline_to_featureset():
    imgs = [_img(40, 40) for _ in range(6)]
    labels = [1, 2, 1, 2, 1, 2]
    iset = ImageSet.from_arrays(imgs, labels)
    iset = iset.transform(ImageResize(24, 24))
    iset = iset.transform(ImageChannelNormalize(120, 120, 120, 50, 50, 50))
    fs = iset.to_feature_set()
    assert fs.size() == 6
    bx, by, bw = next(iter(fs.batches(4)))
    assert bx.shape == (4, 24, 24, 3)
    assert by.shape == (4, 1)


def test_text_pipeline():
    texts = ["Hello world, hello TPU!", "the quick brown fox", "hello fox"]
    ts = TextSet.from_texts(texts, labels=[0, 1, 1])
    ts.tokenize().normalize().word2idx()
    assert "hello" in ts.get_word_index()
    ts.shape_sequence(6)
    x, y = ts.gen_sample()
    assert x.shape == (3, 6)
    assert y.shape == (3, 1)
    # hello appears 3 times -> most frequent -> index 1
    assert ts.get_word_index()["hello"] == 1


def test_text_word_index_options(tmp_path):
    ts = TextSet.from_texts(["a a a b b c", "a b c d"])
    ts.tokenize().normalize().word2idx(remove_topN=1, max_words_num=2)
    wi = ts.get_word_index()
    assert "a" not in wi and len(wi) == 2
    p = str(tmp_path / "wi.json")
    ts.save_word_index(p)
    ts2 = TextSet.from_texts(["b c"]).tokenize().normalize()
    ts2.load_word_index(p)
    ts2.word2idx(existing_map=ts2.word_index)
    assert ts2.features[0]["indexed_tokens"][0] == wi["b"]


def test_relations():
    rels = [Relation("q1", "d1", 1), Relation("q1", "d2", 0),
            Relation("q1", "d3", 0), Relation("q2", "d1", 1),
            Relation("q2", "d4", 0)]
    pairs = generate_relation_pairs(rels, seed=0)
    assert len(pairs) == 2
    for q, p, n in pairs:
        assert p in ("d1",) and n in ("d2", "d3", "d4")
    lists = generate_relation_lists(rels)
    assert len(lists["q1"]) == 3
    corpus_q = {"q1": [1, 2], "q2": [3, 4]}
    corpus_d = {f"d{i}": [i, i] for i in range(1, 5)}
    q_arr, d_arr = relation_pairs_to_arrays(pairs, corpus_q, corpus_d)
    assert q_arr.shape == (4, 2)  # interleaved pos/neg
    np.testing.assert_array_equal(q_arr[0], q_arr[1])


def test_image3d_ops():
    vol = np.random.default_rng(0).normal(size=(16, 16, 16)).astype(np.float32)
    assert Crop3D((2, 2, 2), (8, 8, 8)).transform(vol).shape == (8, 8, 8)
    assert CenterCrop3D((8, 10, 12)).transform(vol).shape == (8, 10, 12)
    assert RandomCrop3D((8, 8, 8), seed=0).transform(vol).shape == (8, 8, 8)
    rot = Rotate3D(yaw=90).transform(vol)
    assert rot.shape == vol.shape
    ident = AffineTransform3D(np.eye(3)).transform(vol)
    np.testing.assert_allclose(ident, vol, atol=1e-5)
