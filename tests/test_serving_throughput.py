"""Serving hot-path throughput overhaul (PR 3 tentpole): adaptive
micro-batch coalescing, parallel preprocess with quarantine/grouping
semantics preserved, the async device pipeline (dispatch -> downstream
write stage), batched result writes with per-record fallback, amortized
trim, per-stage metrics, and batched client polling."""

import os
import threading
import time

import numpy as np
import pytest

from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
from analytics_zoo_tpu.serving.engine import (ClusterServing, ServingParams,
                                              _LazyResult)
from analytics_zoo_tpu.serving.queues import FileQueue, InProcQueue, RedisQueue
from analytics_zoo_tpu.utils.chaos import FaultInjector

from test_serving_availability import FakeRedis

DIM, NCLS = 3, 4
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.timeout(120)


def _serving(queue, **params):
    from analytics_zoo_tpu.inference.inference_model import InferenceModel
    from analytics_zoo_tpu.nn import Sequential
    from analytics_zoo_tpu.nn.layers import Dense

    model = Sequential()
    model.add(Dense(NCLS, input_shape=(DIM,), activation="softmax"))
    model.init_weights()
    im = InferenceModel().do_load_model(model, model._params, model._state)
    defaults = dict(batch_size=4, poll_timeout_s=0.02, write_backoff_s=0.01,
                    worker_backoff_s=0.01)
    defaults.update(params)
    return ClusterServing(im, queue, params=ServingParams(**defaults))


# -- adaptive micro-batching ---------------------------------------------------

def test_coalescing_batcher_fills_device_batch(ctx):
    """Records that dribble out of the backend one per read are coalesced
    into a single device-sized batch within the max_wait budget."""
    q = InProcQueue()
    serving = _serving(q, batch_size=4, max_batch=8, max_wait_ms=2000)
    orig = q.read_batch
    q.read_batch = lambda n, t: orig(min(n, 1), t)   # backend dribbles
    cin = InputQueue(q)
    for i in range(8):
        cin.enqueue_tensor(f"r{i}", np.ones(DIM, np.float32))
    batch = serving._read_coalesced()
    assert len(batch) == 8                           # one full device batch


def test_coalescing_batcher_releases_partial_batch_at_max_wait(ctx):
    """A partial batch is released once max_wait_ms elapses — coalescing
    bounds latency, it does not hold records hostage for a full batch."""
    q = InProcQueue()
    serving = _serving(q, batch_size=4, max_batch=64, max_wait_ms=50)
    InputQueue(q).enqueue_tensor("r0", np.ones(DIM, np.float32))
    t0 = time.monotonic()
    batch = serving._read_coalesced()
    dt = time.monotonic() - t0
    assert len(batch) == 1
    assert 0.04 <= dt < 5.0                          # waited ~the budget


def test_coalescing_batcher_idle_stream_stays_low_latency(ctx):
    """An EMPTY stream returns within poll_timeout_s: the coalescing wait
    only starts once a first record has arrived to amortize it against."""
    q = InProcQueue()
    serving = _serving(q, batch_size=4, max_batch=64, max_wait_ms=5000,
                       poll_timeout_s=0.02)
    t0 = time.monotonic()
    batch = serving.queue.read_batch(64, 0.01) or serving._read_coalesced()
    assert not batch
    assert time.monotonic() - t0 < 2.0               # no max_wait penalty


def test_default_max_batch_is_batch_size(ctx):
    q = InProcQueue()
    serving = _serving(q, batch_size=4)              # max_batch=None
    cin = InputQueue(q)
    for i in range(12):
        cin.enqueue_tensor(f"r{i}", np.ones(DIM, np.float32))
    assert len(serving._read_coalesced()) == 4       # pre-PR-3 read size


# -- parallel preprocess -------------------------------------------------------

def test_parallel_preprocess_preserves_quarantine_and_grouping(ctx):
    """With a preprocess pool, a malformed record still quarantines ALONE
    and multi-shape batches still re-group by shape downstream."""
    q = InProcQueue()
    serving = _serving(q, batch_size=8, preprocess_workers=4)
    cin = InputQueue(q)
    cin.enqueue_tensor("a0", np.ones(DIM, np.float32))
    cin.enqueue_tensor("a1", np.ones(DIM, np.float32))
    q.xadd({"uri": "bad", "b64": "!!!not-base64!!!", "dtype": "<f4",
            "shape": [DIM]})
    cin.enqueue_tensor("wide0", np.ones((2, DIM), np.float32))
    cin.enqueue_tensor("a2", np.ones(DIM, np.float32))
    groups = serving._read_and_preprocess()
    shapes = sorted(g.tensors.shape for g in groups)
    assert shapes == [(1, 2, DIM), (3, DIM)]         # re-grouped by shape
    by_shape = {g.tensors.shape: g for g in groups}
    assert by_shape[(3, DIM)].ids == ["a0", "a1", "a2"]
    assert by_shape[(1, 2, DIM)].ids == ["wide0"]
    assert [d["uri"] for d in q.dead_letters()] == ["bad"]
    assert OutputQueue.is_error(q.get_result("bad"))
    assert serving._pre_pool is not None             # pool actually in use


def test_parallel_preprocess_end_to_end(ctx):
    """Pipelined loop with a preprocess pool serves a poisoned stream to
    completion — the PR 1 acceptance semantics hold under fan-out."""
    q = InProcQueue()
    serving = _serving(q, batch_size=8, preprocess_workers=4,
                       max_batch=16, max_wait_ms=20)
    cin, cout = InputQueue(q), OutputQueue(q)
    rids = []
    for i in range(20):
        rid = f"r{i}"
        if i in (3, 11):
            q.xadd({"uri": rid, "b64": "%%%", "dtype": "<f4",
                    "shape": [DIM]})
        else:
            cin.enqueue_tensor(rid, np.ones(DIM, np.float32))
        rids.append(rid)
    serving.start()
    try:
        got = cout.query_many(rids, timeout_s=30)
        assert all(r is not None for r in got.values())
        errs = [rid for rid, r in got.items() if OutputQueue.is_error(r)]
        assert sorted(errs) == ["r11", "r3"]
        assert serving.total_records == 18
    finally:
        serving.shutdown()
    assert serving._pre_pool is None                 # shutdown released it


# -- async device pipeline -----------------------------------------------------

def test_dispatch_matches_do_predict(ctx):
    """InferenceModel.dispatch + .result() == do_predict, including bucket
    padding (n=5 -> pow-2 bucket 8) and the int8 scales path."""
    from analytics_zoo_tpu.inference.inference_model import InferenceModel
    from analytics_zoo_tpu.nn import Sequential
    from analytics_zoo_tpu.nn.layers import Dense, Flatten

    model = Sequential()
    model.add(Flatten(input_shape=(4, 3)))
    model.add(Dense(5, activation="softmax"))
    model.init_weights()
    im = InferenceModel().do_load_model(model, model._params, model._state)

    g = np.random.default_rng(0)
    x = g.normal(size=(5, 4, 3)).astype(np.float32)
    np.testing.assert_allclose(im.dispatch(x).result(), im.do_predict(x),
                               rtol=1e-5, atol=1e-6)
    qx = g.integers(-127, 127, (5, 4, 3)).astype(np.int8)
    scales = g.uniform(0.01, 0.1, (5,)).astype(np.float32)
    np.testing.assert_allclose(im.dispatch(qx, scales=scales).result(),
                               im.do_predict(qx, scales=scales),
                               rtol=1e-5, atol=1e-6)


def test_engine_uses_async_dispatch_unless_model_is_patched(ctx):
    """The hot path dispatches asynchronously; an instance-patched
    do_predict (chaos wrappers, user shims) stays on the hot path via the
    lazy synchronous fallback."""
    serving = _serving(InProcQueue())
    h = serving._dispatch_batch(np.ones((2, DIM), np.float32), None)
    assert not isinstance(h, _LazyResult)            # real async dispatch
    assert h.result().shape == (2, NCLS)

    serving.model.do_predict = \
        lambda x, scales=None: np.full((len(x), NCLS), 0.25)
    h2 = serving._dispatch_batch(np.ones((2, DIM), np.float32), None)
    assert isinstance(h2, _LazyResult)
    assert h2.result().shape == (2, NCLS)

    # a CLASS-level do_predict override (user subclass) must be honored
    # too — the base dispatch would silently bypass it
    from analytics_zoo_tpu.inference.inference_model import InferenceModel

    class Shimmed(InferenceModel):
        def do_predict(self, x, batch_size=None, scales=None):
            return np.full((len(x), NCLS), 0.5)

    serving2 = _serving(InProcQueue())
    shim = Shimmed()
    shim.do_load_model(serving2.model._model)
    serving2.model = shim
    h3 = serving2._dispatch_batch(np.ones((2, DIM), np.float32), None)
    assert isinstance(h3, _LazyResult)
    assert (h3.result() == 0.5).all()


def test_drain_flushes_dispatched_inflight_batches(ctx):
    """Graceful drain under the ASYNC pipeline: batches sitting dispatched
    in the write queue (slow result store) are all flushed before exit."""
    q = InProcQueue()
    serving = _serving(q, batch_size=4, inflight_batches=4)
    orig = q.put_results

    def slow_put_results(pairs):
        time.sleep(0.05)                  # writer becomes the bottleneck
        return orig(pairs)

    q.put_results = slow_put_results
    cin = InputQueue(q)
    rids = [cin.enqueue_tensor(f"r{i}", np.ones(DIM, np.float32))
            for i in range(32)]
    serving.start()
    time.sleep(0.1)                       # write queue fills
    serving.shutdown(drain_s=30.0)
    got = {rid: q.get_result(rid) for rid in rids}
    missing = [rid for rid, r in got.items() if r is None]
    assert not missing, f"drain dropped {missing}"
    assert all(not OutputQueue.is_error(r) for r in got.values())
    assert serving.total_records == 32


# -- batched result writes -----------------------------------------------------

def test_put_results_all_backends(tmp_path):
    for q in (InProcQueue(), FileQueue(str(tmp_path / "q")),
              RedisQueue(client=FakeRedis())):
        q.put_results([("a", {"value": [1]}), ("b", {"value": [2]})])
        assert q.get_result("a") == {"value": [1]}
        assert q.get_result("b") == {"value": [2]}
        assert q.result_count() == 2
        got = q.get_results(["a", "b", "missing"])
        assert got == {"a": {"value": [1]}, "b": {"value": [2]},
                       "missing": None}


def test_batch_write_failure_falls_back_without_loss(ctx):
    """A failing batch write degrades to per-record writes: every record
    still resolves exactly once, nothing quarantined."""
    q = InProcQueue()
    serving = _serving(q, write_retries=0)
    inj = FaultInjector().fail("put_results", times=99, exc=ConnectionError)
    q.put_results = inj.wrap("put_results", q.put_results)
    cin = InputQueue(q)
    rids = [cin.enqueue_tensor(f"r{i}", np.ones(DIM, np.float32))
            for i in range(4)]
    assert serving.serve_once() == 4
    for rid in rids:
        assert not OutputQueue.is_error(q.get_result(rid))
    assert q.result_count() == 4                    # no loss, no duplication
    assert serving.dead_lettered == 0


def test_batch_write_midway_failure_quarantines_only_failing_record(ctx):
    """Batch write down + ONE record's fallback write also failing: the
    other records commit, only the culprit is dead-lettered."""
    q = InProcQueue()
    serving = _serving(q, write_retries=0)
    inj = FaultInjector()
    inj.fail("put_results", times=99, exc=ConnectionError)
    inj.fail_when("put_result", lambda c: c["args"][0] == "r2",
                  exc=ConnectionError)
    q.put_results = inj.wrap("put_results", q.put_results)
    q.put_result = inj.wrap("put_result", q.put_result)
    cin = InputQueue(q)
    rids = [cin.enqueue_tensor(f"r{i}", np.ones(DIM, np.float32))
            for i in range(4)]
    assert serving.serve_once() == 3
    for rid in rids:
        res = q.get_result(rid)
        assert res is not None
        assert OutputQueue.is_error(res) == (rid == "r2")
    assert [d["uri"] for d in q.dead_letters()] == ["r2"]
    assert serving.dead_lettered == 1


def test_trim_runs_on_amortized_schedule(ctx):
    """Satellite regression: trim used to cost one backend round-trip per
    micro-batch; now it follows trim_interval_s (0 restores per-batch)."""
    # amortized: a long interval means ZERO trims across many batches
    q = InProcQueue()
    serving = _serving(q, trim_interval_s=3600.0)
    inj = FaultInjector()
    q.trim = inj.wrap("trim", q.trim)
    cin = InputQueue(q)
    for i in range(12):
        cin.enqueue_tensor(f"r{i}", np.ones(DIM, np.float32))
    while serving.serve_once():
        pass
    assert inj.count("trim") == 0
    # elapsed interval: exactly one trim fires, then the clock re-arms
    serving._last_trim = time.monotonic() - 7200.0
    cin.enqueue_tensor("late", np.ones(DIM, np.float32))
    serving.serve_once()
    assert inj.count("trim") == 1
    # interval 0: the pre-PR-3 per-batch behaviour
    q2 = InProcQueue()
    serving2 = _serving(q2, trim_interval_s=0.0)
    inj2 = FaultInjector()
    q2.trim = inj2.wrap("trim", q2.trim)
    cin2 = InputQueue(q2)
    for i in range(12):
        cin2.enqueue_tensor(f"r{i}", np.ones(DIM, np.float32))
    while serving2.serve_once():
        pass
    assert inj2.count("trim") == 3                  # 12 records / batch 4


# -- per-stage metrics ---------------------------------------------------------

def test_stage_metrics_and_latency_populated(ctx):
    q = InProcQueue()
    serving = _serving(q, batch_size=4)
    cin, cout = InputQueue(q), OutputQueue(q)
    rids = [cin.enqueue_tensor(f"r{i}", np.ones(DIM, np.float32))
            for i in range(8)]
    serving.start()
    try:
        got = cout.query_many(rids, timeout_s=30)
        assert all(r is not None for r in got.values())
        m = serving.metrics()
        for stage in ("read", "preprocess", "stage_wait", "predict",
                      "write"):
            assert m["stages"][stage]["count"] > 0, stage
            assert m["stages"][stage]["p50_ms"] is not None, stage
            assert m["stages"][stage]["p99_ms"] is not None, stage
        assert m["stages"]["e2e"]["count"] == 8
        assert m["latency_ms"]["p50"] is not None
        assert m["latency_ms"]["p99"] >= m["latency_ms"]["p50"]
        # health() carries the same stage document
        h = serving.health()
        assert h["stages"] is not None
        assert set(h["stages"]) == set(m["stages"])
    finally:
        serving.shutdown()


# -- batched client polling ----------------------------------------------------

def test_query_many_uses_batched_reads_with_backoff(ctx):
    """A many-record query costs one get_results round-trip per poll sweep
    (never per-id reads), and the sweep interval backs off."""
    q = InProcQueue()
    inj = FaultInjector()
    q.get_result = inj.wrap("get_result", q.get_result)
    q.get_results = inj.wrap("get_results", q.get_results)
    for i in range(50):
        q.put_result(f"r{i}", {"value": [i]})
    uris = [f"r{i}" for i in range(50)] + ["missing"]
    out = OutputQueue(q).query_many(uris, timeout_s=0.3)
    assert sum(1 for r in out.values() if r is not None) == 50
    assert out["missing"] is None
    assert inj.count("get_result") == 0             # never per-id
    assert 1 <= inj.count("get_results") <= 20      # backoff bounds sweeps


def test_query_single_backs_off(ctx):
    q = InProcQueue()
    inj = FaultInjector()
    q.get_result = inj.wrap("get_result", q.get_result)
    out = OutputQueue(q).query("nope", timeout_s=0.5, poll_s=0.01)
    assert out is None
    # fixed 0.01 polling would need ~50 reads; backoff caps it far lower
    assert inj.count("get_result") <= 20


def test_dequeue_is_one_round_trip(ctx):
    fake = FakeRedis()
    q = RedisQueue(client=fake)
    inj = FaultInjector()
    fake.hmget = inj.wrap("hmget", fake.hmget)
    fake.hget = inj.wrap("hget", fake.hget)
    q.put_results([(f"r{i}", {"value": [i]}) for i in range(16)])
    out = OutputQueue(q).dequeue([f"r{i}" for i in range(16)])
    assert len(out) == 16 and all(r is not None for r in out.values())
    assert inj.count("hmget") == 1 and inj.count("hget") == 0


# -- O(n) top-N postprocess ----------------------------------------------------

def test_argpartition_postprocess_matches_argsort(ctx):
    from analytics_zoo_tpu.serving.engine import default_postprocess
    g = np.random.default_rng(0)
    for width in (3, 5, 17, 1000):
        probs = g.random(width).astype(np.float32)
        got = default_postprocess(probs, top_n=5)
        idx = np.argsort(-probs)[:5]
        want = [[int(i), float(probs[i])] for i in idx]
        assert got == want, width


# -- bench smoke + sweep (CI/tooling satellite) --------------------------------

def _bench_main():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "serving_bench", os.path.join(REPO, "tools", "serving_bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


def test_serving_bench_smoke_mode(ctx):
    """`serving_bench.py --smoke` completes inside the tier-1 budget and
    asserts the pipeline + stage metrics itself; here we just re-check the
    returned document."""
    out = _bench_main()(["--smoke", "--n", "48"])
    assert out["records"] == 48
    assert out["stages"]["e2e"]["count"] == 48
    assert out["latency_ms"]["p99"] is not None


@pytest.mark.slow
def test_serving_bench_batching_sweep(ctx):
    """Throughput sweep across batch sizes (slow: excluded from tier-1)."""
    outs = _bench_main()(["--smoke", "--n", "96", "--sweep", "4,8,16"])
    assert [o["batch_size"] for o in outs] == [4, 8, 16]
    for o in outs:
        assert o["records"] == 96


def test_threaded_enqueue_while_serving(ctx):
    """Coalescing + async pipeline under a LIVE trickle (not pre-filled):
    all records resolve, none lost between the stage hand-offs."""
    q = InProcQueue()
    serving = _serving(q, batch_size=4, max_batch=16, max_wait_ms=10,
                       preprocess_workers=2, inflight_batches=3)
    cin, cout = InputQueue(q), OutputQueue(q)
    rids = [f"r{i}" for i in range(60)]

    def feed():
        for rid in rids:
            cin.enqueue_tensor(rid, np.ones(DIM, np.float32))
            time.sleep(0.002)

    t = threading.Thread(target=feed)
    serving.start()
    try:
        t.start()
        got = cout.query_many(rids, timeout_s=30)
        t.join()
        assert all(r is not None for r in got.values())
        assert all(not OutputQueue.is_error(r) for r in got.values())
        assert serving.total_records == 60
    finally:
        serving.shutdown()
