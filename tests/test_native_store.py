"""Native C++ sample store (csrc/sample_store.cpp) via ctypes."""

import numpy as np
import pytest

from analytics_zoo_tpu.feature.dataset import NativeFeatureSet
from analytics_zoo_tpu.utils.native import NativeSampleStore


def test_store_write_gather_roundtrip():
    st = NativeSampleStore(100, (8, 4), np.float32)
    data = np.arange(100 * 32, dtype=np.float32).reshape(100, 8, 4)
    st.write_bulk(0, data)
    got = st.gather(np.asarray([0, 99, 50, 50]))
    np.testing.assert_array_equal(got, data[[0, 99, 50, 50]])
    st.close()


def test_store_mmap_tier(tmp_path):
    p = str(tmp_path / "arena.bin")
    st = NativeSampleStore(64, (16,), np.float32, path=p)
    st.write_bulk(0, np.full((64, 16), 7.0, np.float32))
    assert st.gather(np.asarray([63]))[0].sum() == 7.0 * 16
    st.close()
    import os
    assert os.path.getsize(p) == 64 * 16 * 4


def test_store_bad_index_raises():
    st = NativeSampleStore(10, (4,), np.float32)
    st.write_bulk(0, np.zeros((10, 4), np.float32))
    with pytest.raises(IndexError):
        st.gather(np.asarray([11]))
    st.close()


def test_native_featureset_batches(ctx):
    g = np.random.default_rng(0)
    x = g.normal(size=(130, 6)).astype(np.float32)
    y = g.normal(size=(130, 1)).astype(np.float32)
    fs = NativeFeatureSet(x, y)
    batches = list(fs.batches(64, shuffle=True, rng=np.random.default_rng(1)))
    assert len(batches) == 3
    assert batches[-1][2].sum() == 130 - 128  # padding weights zero
    total = sum(int(b[2].sum()) for b in batches)
    assert total == 130
    fs.close()
