"""int8 post-training quantization tests (VERDICT r2 #5).

Done criterion: <1% top-1 disagreement vs the float model on a synthetic
eval, through the InferenceModel surface; numeric closeness on dense/conv
layers; float fallback for uncalibrated layers.
"""

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.inference.inference_model import InferenceModel
from analytics_zoo_tpu.inference.quantize import (
    calibrate, quantize, quantize_params)
from analytics_zoo_tpu.nn import Sequential
from analytics_zoo_tpu.nn.layers import (
    Convolution2D, Dense, Flatten, GlobalAveragePooling2D, MaxPooling2D)
from analytics_zoo_tpu.nn.optimizers import Adam


def _trained_mlp(rng, n_classes=5, d=12):
    """Small trained classifier so logits carry real structure."""
    x = rng.normal(size=(512, d)).astype(np.float32)
    w_true = rng.normal(size=(d, n_classes)).astype(np.float32)
    y = x @ w_true
    labels = y.argmax(-1).astype(np.float32)[:, None]
    m = Sequential()
    m.add(Dense(32, activation="relu", input_shape=(d,)))
    m.add(Dense(n_classes, activation="softmax"))
    m.compile(optimizer=Adam(lr=0.01),
              loss="sparse_categorical_crossentropy")
    m.fit(x, labels, batch_size=64, nb_epoch=10, verbose=False)
    return m, x


def test_quantized_dense_close_to_float(rng):
    m, x = _trained_mlp(rng)          # Sequential: the container itself
    params, state = m._params, m._state
    xj = jnp.asarray(x[:64])
    y_fp = np.asarray(m.predict(x[:64], batch_size=64))
    qp = quantize(m, params, state, jnp.asarray(x[:256]))
    y_q = np.asarray(m.apply(qp, state, xj, training=False)[0])
    # probabilities close, argmax nearly always identical
    assert np.abs(y_q - y_fp).max() < 0.05
    agree = (y_q.argmax(-1) == y_fp.argmax(-1)).mean()
    assert agree > 0.99


def test_quantize_via_inference_model_top1_parity(rng):
    m, x = _trained_mlp(rng)
    im_fp = InferenceModel().do_load_model(m, m._params, m._state)
    y_fp = im_fp.do_predict(x, batch_size=128)

    im_q = InferenceModel().do_load_model(m, m._params, m._state)
    im_q.do_quantize(jnp.asarray(x[:256]), force=True)
    y_q = im_q.do_predict(x, batch_size=128)
    disagree = (y_q.argmax(-1) != y_fp.argmax(-1)).mean()
    assert disagree < 0.01, disagree         # <1% top-1 drop criterion
    # weights really are int8
    ql = [v for v in im_q._params.values()
          if isinstance(v, dict) and "W_q" in v]
    assert len(ql) == 2
    assert all(q["W_q"].dtype == jnp.int8 for q in ql)


def test_quantized_conv_model(rng):
    m = Sequential()
    m.add(Convolution2D(8, 3, activation="relu", border_mode="same",
                        input_shape=(12, 12, 3)))
    m.add(MaxPooling2D(2))
    m.add(Convolution2D(16, 3, activation="relu"))
    m.add(GlobalAveragePooling2D())
    m.add(Dense(4, activation="softmax"))
    m.init_weights()
    x = rng.normal(size=(32, 12, 12, 3)).astype(np.float32)
    params, state = m._params, m._state
    y_fp = np.asarray(m.predict(x, batch_size=32))
    qp = quantize(m, params, state, jnp.asarray(x))
    y_q = np.asarray(m.apply(qp, state, jnp.asarray(x), training=False)[0])
    assert np.abs(y_q - y_fp).max() < 0.06
    assert (y_q.argmax(-1) == y_fp.argmax(-1)).mean() > 0.95


def test_uncalibrated_layer_stays_float(rng):
    m = Sequential()
    m.add(Dense(8, input_shape=(4,), name="d0"))
    m.init_weights()
    params = m._params
    # absmax missing for d0 -> untouched
    qp = quantize_params(m, params, {})
    assert "W" in qp["d0"] and "W_q" not in qp["d0"]


def test_calibrate_restores_call_methods(rng):
    m = Sequential()
    m.add(Dense(8, input_shape=(4,), name="d0"))
    m.init_weights()
    x = jnp.asarray(rng.normal(size=(4, 4)), jnp.float32)
    layer = m.layers_list[0]
    absmax = calibrate(m, m._params, m._state, x)
    assert absmax["d0"] > 0
    assert "call" not in vars(layer)     # instance wrapper removed


def test_do_quantize_defaults_to_noop_with_warning(rng):
    import warnings
    m, x = _trained_mlp(rng)
    im = InferenceModel().do_load_model(m, m._params, m._state)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        im.do_quantize(jnp.asarray(x[:64]))   # no force -> warn + no-op
    assert any("force=True" in str(x.message) for x in w)
    assert not [v for v in im._params.values()
                if isinstance(v, dict) and "W_q" in v]
