"""Regression tests for round-2 advisor findings (ADVICE.md round 2):

1. Merge.call with stateful branches must refuse at *inference* too (previously
   only training=True raised; inference silently used freshly-initialised
   BatchNorm statistics), and must accept an explicit trained state= kwarg.
2. ZooConf.from_env must tolerate dataclass fields declared with
   default_factory (previously getattr(ZooConf, name) raised AttributeError).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.common.context import ZooConf
from analytics_zoo_tpu.nn.layers.core import BatchNormalization, Dense, Merge


def _stateful_merge():
    m = Merge(mode="sum")
    m.branches = [BatchNormalization(input_shape=(4,), name="bn0"),
                  Dense(4, input_shape=(4,), name="d0")]
    m._declared_input_shape = [(None, 4), (None, 4)]
    return m


def test_merge_call_stateful_raises_at_inference(rng):
    m = _stateful_merge()
    params = {b.name: b.build(jax.random.PRNGKey(i), (2, 4))
              for i, b in enumerate(m.branches)}
    x = [np.asarray(rng.normal(size=(2, 4)), np.float32)] * 2
    with pytest.raises(RuntimeError, match="stateful"):
        m.call(params, x, training=False)
    with pytest.raises(RuntimeError, match="stateful"):
        m.call(params, x, training=True)


def test_merge_call_accepts_explicit_state(rng):
    m = _stateful_merge()
    params = {b.name: b.build(jax.random.PRNGKey(i), (2, 4))
              for i, b in enumerate(m.branches)}
    state = m.init_state(m._declared_input_shape)
    x = [np.asarray(rng.normal(size=(2, 4)), np.float32)] * 2
    y = m.call(params, x, training=False, state=state)
    y2, _ = m.apply(params, state, x, training=False)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y2))
    with pytest.raises(RuntimeError, match="inference-only"):
        m.call(params, x, training=True, state=state)


def test_from_env_tolerates_default_factory(monkeypatch):
    @dataclasses.dataclass
    class Conf2(ZooConf):
        extras: list = dataclasses.field(default_factory=list)

    monkeypatch.setenv("ZOO_TPU_SEED", "99")
    monkeypatch.setenv("ZOO_TPU_EXTRAS", "whatever")
    conf = Conf2.from_env()          # previously AttributeError on `extras`
    assert conf.seed == 99
    assert conf.extras == ["whatever"]   # list fields parse comma-separated


def test_autograd_round3_functions(rng):
    """AutoGrad math parity additions: erf/slice/index_select/squeeze/expand
    (math.scala:32-378)."""
    from scipy.special import erf as scipy_erf

    from analytics_zoo_tpu.nn import Input, Model, autograd

    x = np.asarray(rng.normal(size=(3, 4, 5)), np.float32)

    def run(sym_out, inp):
        m = Model(input=inp, output=sym_out)
        params, _ = m.init(jax.random.PRNGKey(0))
        return np.asarray(m.call(params, jnp.asarray(x), training=False))

    v = Input(shape=(4, 5))
    np.testing.assert_allclose(run(autograd.erf(v), v), scipy_erf(x),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(run(autograd.slice(v, 0, 1, 2), v),
                               x[:, 1:3], rtol=1e-6)
    np.testing.assert_allclose(run(autograd.slice(v, 1, 2, -1), v),
                               x[:, :, 2:], rtol=1e-6)
    np.testing.assert_allclose(run(autograd.index_select(v, 1, [0, 3]), v),
                               x[:, :, [0, 3]], rtol=1e-6)
    np.testing.assert_allclose(run(autograd.index_select(v, 0, 2), v),
                               x[:, 2], rtol=1e-6)
    # expand_dims uses raw array axes (axis 1 = first non-batch slot)
    np.testing.assert_allclose(run(autograd.expand(
        autograd.expand_dims(v, 1), (4, -1, -1)), v)[:, 1],
        x, rtol=1e-6)
    np.testing.assert_allclose(
        run(autograd.squeeze(autograd.expand_dims(v, 1), 0), v), x, rtol=1e-6)
    np.testing.assert_allclose(run(autograd.contiguous(v), v), x)


def test_autograd_slice_negative_start_and_bad_index(rng):
    from analytics_zoo_tpu.nn import Input, Model, autograd

    x = np.asarray(rng.normal(size=(2, 3, 4)), np.float32)
    v = Input(shape=(3, 4))
    m = Model(input=v, output=autograd.slice(v, 1, -2, 2))
    params, _ = m.init(jax.random.PRNGKey(0))
    got = np.asarray(m.call(params, jnp.asarray(x), training=False))
    np.testing.assert_allclose(got, x[:, :, -2:], rtol=1e-6)

    with pytest.raises(IndexError, match="out of range"):
        m2 = Model(input=v, output=autograd.index_select(v, 1, 99))
        p2, _ = m2.init(jax.random.PRNGKey(0))
        m2.call(p2, jnp.asarray(x), training=False)
