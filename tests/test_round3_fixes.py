"""Regression tests for round-2 advisor findings (ADVICE.md round 2):

1. Merge.call with stateful branches must refuse at *inference* too (previously
   only training=True raised; inference silently used freshly-initialised
   BatchNorm statistics), and must accept an explicit trained state= kwarg.
2. ZooConf.from_env must tolerate dataclass fields declared with
   default_factory (previously getattr(ZooConf, name) raised AttributeError).
"""

import dataclasses

import jax
import numpy as np
import pytest

from analytics_zoo_tpu.common.context import ZooConf
from analytics_zoo_tpu.nn.layers.core import BatchNormalization, Dense, Merge


def _stateful_merge():
    m = Merge(mode="sum")
    m.branches = [BatchNormalization(input_shape=(4,), name="bn0"),
                  Dense(4, input_shape=(4,), name="d0")]
    m._declared_input_shape = [(None, 4), (None, 4)]
    return m


def test_merge_call_stateful_raises_at_inference(rng):
    m = _stateful_merge()
    params = {b.name: b.build(jax.random.PRNGKey(i), (2, 4))
              for i, b in enumerate(m.branches)}
    x = [np.asarray(rng.normal(size=(2, 4)), np.float32)] * 2
    with pytest.raises(RuntimeError, match="stateful"):
        m.call(params, x, training=False)
    with pytest.raises(RuntimeError, match="stateful"):
        m.call(params, x, training=True)


def test_merge_call_accepts_explicit_state(rng):
    m = _stateful_merge()
    params = {b.name: b.build(jax.random.PRNGKey(i), (2, 4))
              for i, b in enumerate(m.branches)}
    state = m.init_state(m._declared_input_shape)
    x = [np.asarray(rng.normal(size=(2, 4)), np.float32)] * 2
    y = m.call(params, x, training=False, state=state)
    y2, _ = m.apply(params, state, x, training=False)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y2))
    with pytest.raises(RuntimeError, match="inference-only"):
        m.call(params, x, training=True, state=state)


def test_from_env_tolerates_default_factory(monkeypatch):
    @dataclasses.dataclass
    class Conf2(ZooConf):
        extras: list = dataclasses.field(default_factory=list)

    monkeypatch.setenv("ZOO_TPU_SEED", "99")
    monkeypatch.setenv("ZOO_TPU_EXTRAS", "whatever")
    conf = Conf2.from_env()          # previously AttributeError on `extras`
    assert conf.seed == 99
    assert conf.extras == ["whatever"]   # list fields parse comma-separated
