"""Usage metering & attribution (PR 19).

Covers the metering tentpole end to end: the UsageMeter's tenant folding
(absent -> "unknown", junk -> normalized, cardinality cap -> "other"),
device-second conservation (Σ tenant shares == measured dispatch wall),
delta-drain journal semantics, the engine's attribution of records /
sheds / device seconds / per-tenant SLO burn views through real served
traffic (all three queue backends for the legacy-record path), the
durable usage journal (tracecollect rotation + clock contract + `manager
usage` rollup), fleet aggregation of per-tenant usage, and the hostile
label-escaping hardening for merge_prometheus.  The real-process
acceptance test (2 replicas behind the LB, two tenants, `manager usage`
rollup matching the client's own counts exactly, journal surviving
`manager stop`) is `slow`-marked like the PR 10/15/16 chaos tests.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from analytics_zoo_tpu.common.observability import MetricsRegistry
from analytics_zoo_tpu.inference.inference_model import InferenceModel
from analytics_zoo_tpu.nn import Sequential
from analytics_zoo_tpu.nn.layers import Dense
from analytics_zoo_tpu.serving import fleet, tracecollect
from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
from analytics_zoo_tpu.serving.engine import ClusterServing, ServingParams
from analytics_zoo_tpu.serving.metering import UNKNOWN_TENANT, UsageMeter
from analytics_zoo_tpu.serving.queues import FileQueue, InProcQueue, RedisQueue

from test_serving_availability import FakeRedis

pytestmark = pytest.mark.metering

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIM = 16
NCLS = 8


def _model():
    m = Sequential()
    m.add(Dense(NCLS, activation="softmax", input_shape=(DIM,)))
    m.init_weights()
    return InferenceModel().do_load_model(m, m._params, m._state)


def _serving(q, **params):
    return ClusterServing(_model(), q,
                          params=ServingParams(batch_size=4, **params))


def _records_counter(reg):
    return reg.counter("serving_records_total", labels=("tenant", "model"))


def _serve_all(serving):
    while serving.serve_once():
        pass


# -- meter unit behavior -------------------------------------------------------

def test_meter_resolve_folds_absent_junk_and_overflow():
    """Absent identity -> "unknown"; junk ids normalize at the same edge
    admission uses; past max_tenants DISTINCT ids everything folds into
    "other" so a tenant-id sweep cannot grow the exposition."""
    meter = UsageMeter(MetricsRegistry(), cfg={"max_tenants": 3})
    assert meter.resolve(None) == UNKNOWN_TENANT
    assert meter.resolve("") == UNKNOWN_TENANT
    assert meter.resolve("Acme-1") == "Acme-1"      # well-formed: kept
    assert meter.resolve("!!!") == "other"          # junk -> other lane
    assert meter.resolve("t2") == "t2"
    assert meter.resolve("t3") == "t3"              # hits the cap (3rd id)
    assert meter.resolve("t4") == "other"           # over the cap
    assert meter.resolve("Acme-1") == "Acme-1"      # seen ids stay stable
    # the sentinel lanes never count against the cap
    assert meter.resolve("default") == "default"
    assert meter.resolve("other") == "other"


def test_meter_device_seconds_conserves_wall():
    """Σ per-tenant shares == the batch's measured wall time exactly —
    the invariant that makes per-tenant device seconds sum to engine busy
    time by construction."""
    reg = MetricsRegistry()
    meter = UsageMeter(reg, model="v7")
    meter.device_seconds({"a": 3, "b": 1, None: 4}, 0.8)
    dev = reg.counter("serving_device_seconds_total",
                      labels=("tenant", "model"))
    a = dev.labels(tenant="a", model="v7").value
    b = dev.labels(tenant="b", model="v7").value
    u = dev.labels(tenant=UNKNOWN_TENANT, model="v7").value
    assert a == pytest.approx(0.3)
    assert b == pytest.approx(0.1)
    assert u == pytest.approx(0.4)
    assert a + b + u == pytest.approx(0.8, abs=1e-12)
    # zero-row and zero-wall batches charge nothing
    meter.device_seconds({}, 0.5)
    meter.device_seconds({"a": 4}, 0.0)
    assert dev.labels(tenant="a", model="v7").value == pytest.approx(0.3)


def test_meter_drain_is_per_interval_delta():
    """drain() hands back per-(tenant, model) deltas since the LAST drain
    and resets them — replaying the journal reproduces the counters —
    while snapshot() keeps the cumulative totals."""
    meter = UsageMeter(MetricsRegistry(), model="m1")
    meter.records("acme", 3)
    meter.tokens("acme", 10)
    meter.sheds(None)
    first = meter.drain()
    by_tenant = {r["tenant"]: r for r in first}
    assert by_tenant["acme"]["records"] == 3
    assert by_tenant["acme"]["tokens"] == 10
    assert by_tenant["acme"]["model"] == "m1"
    assert by_tenant[UNKNOWN_TENANT]["sheds"] == 1
    assert all("ts" in r for r in first)
    assert meter.drain() == []                    # nothing new: empty
    meter.records("acme", 2)
    second = meter.drain()
    assert [r["records"] for r in second] == [2]  # the DELTA, not 5
    snap = meter.snapshot()
    assert snap["tenants"]["acme"]["records"] == 5   # cumulative
    assert snap["tenants"][UNKNOWN_TENANT]["sheds"] == 1
    assert snap["enabled"] is True and snap["model"] == "m1"


def test_meter_disabled_registers_pre_pr19_series():
    """metering {"enabled": false}: the historical UNLABELLED records /
    tokens counters come back and the attribution/journal hop is a no-op
    — the off arm of `serving_bench --metering-overhead`."""
    reg = MetricsRegistry()
    meter = UsageMeter(reg, cfg={"enabled": False})
    meter.records("acme", 4)
    meter.tokens("acme", 9)
    meter.sheds("acme")
    meter.device_seconds({"acme": 2}, 0.5)
    meter.request_seconds("acme", 0.1)
    meter.slo_observe("acme", 0.1)
    assert reg.counter("serving_records_total").value == 4
    assert reg.counter("serving_generated_tokens_total").value == 9
    assert reg.get("serving_sheds_total") is None
    assert reg.get("serving_device_seconds_total") is None
    assert meter.drain() == []
    assert meter.snapshot()["enabled"] is False


def test_meter_materializes_configured_tenants_at_zero():
    """Satellite: tenants listed in the admission table exist as labelled
    series from construction — dashboards and the fleet merge never gap
    on first traffic."""
    reg = MetricsRegistry()
    UsageMeter(reg, tenants_configured=("gold", "Bronze-2"))
    text = reg.to_prometheus()
    assert 'serving_records_total{tenant="gold",model="default"} 0' in text
    assert 'serving_records_total{tenant="Bronze-2",model="default"} 0' \
        in text
    assert 'serving_sheds_total{tenant="gold",model="default"} 0' in text
    assert 'serving_device_seconds_total{tenant="gold",model="default"} 0' \
        in text


# -- label-escaping hardening (satellite 1) ------------------------------------

def test_hostile_tenant_label_round_trips_merge_prometheus():
    """A tenant value carrying every escape-worthy byte (quote, backslash,
    newline) renders as valid exposition AND round-trips merge_prometheus
    — the merged fleet text sums the series instead of corrupting it."""
    hostile = 'evil"t\\en\nant'
    reg = MetricsRegistry()
    c = reg.counter("serving_records_total", "Records served",
                    labels=("tenant", "model"))
    c.labels(tenant=hostile, model="default").inc(3)
    text = reg.to_prometheus()
    escaped = 'evil\\"t\\\\en\\nant'
    line = ('serving_records_total{tenant="' + escaped
            + '",model="default"} 3')
    # the whole series renders as ONE exposition line: the raw newline
    # never leaks into the text
    assert line in text.splitlines()
    merged = fleet.merge_prometheus([text, text])
    assert ('serving_records_total{tenant="' + escaped
            + '",model="default"} 6') in merged
    # and the merged text is still parseable exposition (merge of the
    # merge keeps summing, which only works if labels survived intact)
    assert ('serving_records_total{tenant="' + escaped
            + '",model="default"} 12') in fleet.merge_prometheus(
                [merged, merged])


# -- engine attribution (served traffic) ---------------------------------------

def test_engine_attributes_two_tenants_and_legacy(ctx):
    """Tenant-stamped records bill their tenant, legacy records bill
    "unknown", results carry the attribution, and health()["usage"]
    reports the same cumulative totals."""
    q = InProcQueue()
    serving = _serving(q)
    for i in range(5):
        q.xadd({"uri": f"a{i}", "data": [0.1] * DIM, "tenant": "acme"})
    for i in range(3):
        q.xadd({"uri": f"z{i}", "data": [0.2] * DIM, "tenant": "zeta"})
    q.xadd({"uri": "legacy", "data": [0.3] * DIM})
    _serve_all(serving)
    c = _records_counter(serving.registry)
    assert c.labels(tenant="acme", model="default").value == 5
    assert c.labels(tenant="zeta", model="default").value == 3
    assert c.labels(tenant=UNKNOWN_TENANT, model="default").value == 1
    assert q.get_result("a0").get("tenant") == "acme"
    assert q.get_result("z0").get("tenant") == "zeta"
    assert "tenant" not in q.get_result("legacy")
    usage = serving.health()["usage"]
    assert usage["tenants"]["acme"]["records"] == 5
    assert usage["tenants"]["zeta"]["records"] == 3
    assert usage["tenants"][UNKNOWN_TENANT]["records"] == 1
    # per-tenant request-latency histogram materialized for both tenants
    h = serving.registry.histogram("serving_request_seconds",
                                   labels=("tenant", "model"))
    assert h.labels(tenant="acme", model="default").count == 5
    assert h.labels(tenant="zeta", model="default").count == 3


def test_legacy_records_unknown_across_all_backends(ctx, tmp_path):
    """Acceptance: records without a tenant key serve attributed to
    tenant="unknown" on ALL three queue backends — old producers keep
    working against a metered fleet."""
    for q in (InProcQueue(), FileQueue(str(tmp_path / "q")),
              RedisQueue(client=FakeRedis())):
        serving = _serving(q)
        cin = InputQueue(q)
        rids = [cin.enqueue_tensor(f"r{i}", np.ones(DIM, np.float32))
                for i in range(4)]
        _serve_all(serving)
        got = OutputQueue(q).query_many(rids, timeout_s=30)
        assert all(r is not None and not OutputQueue.is_error(r)
                   for r in got.values()), type(q).__name__
        c = _records_counter(serving.registry)
        assert c.labels(tenant=UNKNOWN_TENANT,
                        model="default").value == 4, type(q).__name__


def test_engine_attributes_sheds_to_their_tenant(ctx):
    """An expired record is shed AGAINST its tenant: the loss shows up in
    serving_sheds_total{tenant=} and in the usage totals, not just the
    fleet-scalar shed counter."""
    q = InProcQueue()
    serving = _serving(q)
    q.xadd({"uri": "doomed", "data": [0.1] * DIM, "tenant": "acme",
            "deadline_ns": 1})                      # expired at birth
    q.xadd({"uri": "fine", "data": [0.1] * DIM, "tenant": "acme"})
    _serve_all(serving)
    sheds = serving.registry.counter("serving_sheds_total",
                                     labels=("tenant", "model"))
    assert sheds.labels(tenant="acme", model="default").value == 1
    assert serving.health()["usage"]["tenants"]["acme"]["sheds"] == 1
    assert serving.health()["usage"]["tenants"]["acme"]["records"] == 1
    assert OutputQueue.is_deadline_exceeded(q.get_result("doomed"))


def test_engine_quarantine_bills_shed_to_tenant(ctx):
    """A poisoned record dead-letters against its tenant — billing sees
    WHO lost a record, not only that one was lost."""
    q = InProcQueue()
    serving = _serving(q)
    q.xadd({"uri": "bad", "b64": "!!!not-base64!!!", "dtype": "<f4",
            "tenant": "zeta"})
    _serve_all(serving)
    sheds = serving.registry.counter("serving_sheds_total",
                                     labels=("tenant", "model"))
    assert sheds.labels(tenant="zeta", model="default").value == 1
    assert OutputQueue.is_error(q.get_result("bad"))


def test_device_seconds_conservation_against_busy_time(ctx):
    """ISSUE invariant: Σ tenant device seconds matches the engine's
    measured predict busy time within 5% (here: exactly, both sides are
    the same measured walls)."""
    q = InProcQueue()
    serving = _serving(q)
    for i in range(20):
        q.xadd({"uri": f"a{i}", "data": [0.1] * DIM, "tenant": "acme"})
        q.xadd({"uri": f"z{i}", "data": [0.2] * DIM, "tenant": "zeta"})
    _serve_all(serving)
    usage = serving.health()["usage"]["tenants"]
    dev_total = sum(v["device_s"] for v in usage.values())
    busy = serving.registry.histogram(
        "serving_stage_seconds", labels=("stage",)) \
        .labels(stage="predict").sum
    assert busy > 0
    assert dev_total == pytest.approx(busy, rel=0.05)
    # and both tenants were actually charged device time
    assert usage["acme"]["device_s"] > 0
    assert usage["zeta"]["device_s"] > 0


def test_per_tenant_burn_gauge_next_to_global(ctx):
    """serving_slo_burn_rate keeps its bare fleet-global sample AND gains
    {tenant=} children for metered tenants — the same metric name, the
    PR 13 consumers unbroken."""
    q = InProcQueue()
    serving = _serving(q, serving_slo={"latency_ms": 500, "window_s": 60,
                                       "target": 0.99})
    q.xadd({"uri": "a0", "data": [0.1] * DIM, "tenant": "acme"})
    q.xadd({"uri": "l0", "data": [0.2] * DIM})
    _serve_all(serving)
    text = serving.registry.to_prometheus()
    lines = [l for l in text.splitlines()
             if l.startswith("serving_slo_burn_rate")]
    assert any(l.startswith("serving_slo_burn_rate ") for l in lines), lines
    assert any(l.startswith('serving_slo_burn_rate{tenant="acme"}')
               for l in lines), lines
    assert any(l.startswith(f'serving_slo_burn_rate{{tenant="'
                            f'{UNKNOWN_TENANT}"}}') for l in lines), lines


def test_metering_disabled_engine_serves_unlabelled(ctx):
    """The off switch restores the pre-PR-19 surface on a REAL engine:
    unlabelled serving_records_total, no usage block content, drain_usage
    empty."""
    q = InProcQueue()
    serving = _serving(q, metering={"enabled": False})
    cin = InputQueue(q)
    rids = [cin.enqueue_tensor(f"r{i}", np.ones(DIM, np.float32))
            for i in range(6)]
    _serve_all(serving)
    got = OutputQueue(q).query_many(rids, timeout_s=30)
    assert all(r is not None for r in got.values())
    assert serving.registry.counter("serving_records_total").value == 6
    assert serving.drain_usage() == []
    assert serving.health()["usage"]["enabled"] is False


# -- generation tokens ---------------------------------------------------------

@pytest.mark.generation
def test_generation_tokens_charged_per_tenant(ctx):
    """The continuous batcher charges generation tokens to each slot's
    tenant at every step boundary: two tenants' labelled token counters
    sum to exactly the tokens the clients got back."""
    import base64

    from test_serving_generate import _echo_im

    q = InProcQueue()
    serving = ClusterServing(
        _echo_im(128), q,
        ServingParams(max_batch=8, max_wait_ms=2.0,
                      generation={"max_active_slots": 4, "max_tokens": 16,
                                  "eos_id": 100, "max_prompt_len": 8}))

    def enq(rid, tokens, tenant, max_tokens):
        arr = np.ascontiguousarray(np.asarray(tokens, "<f4"))
        q.xadd({"uri": rid, "b64": base64.b64encode(arr).decode("ascii"),
                "dtype": "<f4", "shape": list(arr.shape),
                "gen": {"max_tokens": max_tokens}, "tenant": tenant})

    enq("ga", [40], "acme", 6)
    enq("gz", [50], "zeta", 4)
    _serve_all(serving)
    ra, rz = q.get_result("ga"), q.get_result("gz")
    assert ra["value"]["length"] == 6 and ra["tenant"] == "acme"
    assert rz["value"]["length"] == 4 and rz["tenant"] == "zeta"
    tok = serving.registry.counter("serving_generated_tokens_total",
                                   labels=("tenant", "model"))
    assert tok.labels(tenant="acme", model="default").value == 6
    assert tok.labels(tenant="zeta", model="default").value == 4
    usage = serving.health()["usage"]["tenants"]
    assert usage["acme"]["tokens"] == 6 and usage["zeta"]["tokens"] == 4
    # generation device time is attributed too (boundary slot rows)
    assert usage["acme"]["device_s"] > 0


# -- durable usage journal -----------------------------------------------------

def test_journal_round_trip_rotation_and_rollup(ctx, tmp_path):
    """engine.drain_usage -> append_usage -> load_usage -> aggregate_usage
    reproduces the counters; the spool rotates once past max_bytes; the
    clock record wall-stamps every delta for --since filtering."""
    q = InProcQueue()
    serving = _serving(q)
    for i in range(4):
        q.xadd({"uri": f"a{i}", "data": [0.1] * DIM, "tenant": "acme"})
    _serve_all(serving)
    pidfile = str(tmp_path / "cs.pid")
    path = tracecollect.usage_path(pidfile)
    assert path.endswith(".usage.jsonl")
    n = tracecollect.append_usage(path, serving.drain_usage(), source="r0")
    assert n >= 1
    # a second interval from more traffic
    for i in range(2):
        q.xadd({"uri": f"b{i}", "data": [0.2] * DIM, "tenant": "acme"})
    _serve_all(serving)
    tracecollect.append_usage(path, serving.drain_usage(), source="r0")
    recs = tracecollect.load_usage([path])
    assert all("ts_wall" in r and "clock_skewed" not in r for r in recs)
    assert all(r.get("replica_id") == "r0" for r in recs)
    agg = tracecollect.aggregate_usage(recs)
    assert agg["by"] == "tenant"
    assert agg["usage"]["acme"]["records"] == 6    # replay == the counter
    # --since: only deltas drained after the cutoff count
    cut = sorted(r["ts_wall"] for r in recs)[-1]
    agg2 = tracecollect.aggregate_usage(recs, since=cut)
    assert 0 < agg2["usage"]["acme"]["records"] < 6
    # by=model groups the same totals under the model axis
    aggm = tracecollect.aggregate_usage(recs, by="model")
    assert aggm["usage"]["default"]["records"] == 6
    with pytest.raises(ValueError):
        tracecollect.aggregate_usage(recs, by="priority")
    # rotation: a tiny max_bytes rolls the file to .1 and keeps BOTH
    # generations discoverable + loadable
    tracecollect.append_usage(path, [{"ts": 1.0, "tenant": "acme",
                                      "model": "default", "records": 1}],
                              max_bytes=1)
    assert os.path.exists(path + ".1")
    spools = tracecollect.find_usage_spools(pidfile)
    assert set(spools) == {path, path + ".1"}
    total = tracecollect.aggregate_usage(tracecollect.load_usage(spools))
    assert total["usage"]["acme"]["records"] == 7


def test_manager_usage_cli_rollup(ctx, tmp_path, capsys):
    """`manager usage` rolls every replica journal up by tenant or model,
    prints JSON with --json, and fails loudly when no journal exists —
    it must work on a STOPPED deployment."""
    from analytics_zoo_tpu.serving import manager

    pidfile = str(tmp_path / "cs.pid")
    rc = manager.main(["usage", "--pidfile", pidfile, "--json"])
    assert rc == 1
    assert "no usage journals" in capsys.readouterr().err
    # two replica journals, overlapping tenants
    tracecollect.append_usage(
        tracecollect.usage_path(pidfile + ".r0"),
        [{"ts": 1.0, "tenant": "acme", "model": "default",
          "records": 3, "tokens": 5}], source="r0")
    tracecollect.append_usage(
        tracecollect.usage_path(pidfile + ".r1"),
        [{"ts": 2.0, "tenant": "acme", "model": "default", "records": 2},
         {"ts": 2.0, "tenant": "zeta", "model": "default", "records": 4}],
        source="r1")
    rc = manager.main(["usage", "--pidfile", pidfile, "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["journals"] == 2 and doc["intervals"] == 3
    assert doc["usage"]["acme"]["records"] == 5
    assert doc["usage"]["acme"]["tokens"] == 5
    assert doc["usage"]["zeta"]["records"] == 4
    rc = manager.main(["usage", "--pidfile", pidfile, "--by", "model",
                       "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["usage"]["default"]["records"] == 9
    # the human table mentions every tenant and the journal count
    rc = manager.main(["usage", "--pidfile", pidfile])
    assert rc == 0
    out = capsys.readouterr().out
    assert "acme" in out and "zeta" in out and "2 journal(s)" in out


def test_incident_bundles_capture_usage_journals(ctx, tmp_path):
    """The usage journal rides incident bundles like span/event spools —
    the forensic snapshot can answer 'who was burning the fleet'."""
    from analytics_zoo_tpu.serving import incident

    pidfile = str(tmp_path / "cs.pid")
    tracecollect.append_usage(
        tracecollect.usage_path(pidfile),
        [{"ts": 1.0, "tenant": "acme", "model": "default", "records": 1}])
    bundle = incident.capture(pidfile, reason="test")
    assert bundle is not None
    names = os.listdir(bundle)
    assert any(n.endswith(".usage.jsonl") for n in names), names


def test_fleet_aggregation_sums_usage(ctx):
    """aggregate_health sums per-tenant usage across replica health docs;
    docs without a usage block (pre-PR-19 replicas) leave it None."""
    base = {"served": 1, "queue_depth": 0}
    doc0 = dict(base, usage={"enabled": True, "tenants": {
        "acme": {"records": 3, "tokens": 0, "device_s": 0.25,
                 "bytes": 10, "sheds": 0}}})
    doc1 = dict(base, usage={"enabled": True, "tenants": {
        "acme": {"records": 2, "tokens": 4, "device_s": 0.5,
                 "bytes": 0, "sheds": 1},
        "zeta": {"records": 7, "tokens": 0, "device_s": 0.0,
                 "bytes": 0, "sheds": 0}}})
    agg = fleet.aggregate_health({0: doc0, 1: doc1})
    assert agg["usage"]["acme"]["records"] == 5
    assert agg["usage"]["acme"]["device_s"] == pytest.approx(0.75)
    assert agg["usage"]["acme"]["sheds"] == 1
    assert agg["usage"]["zeta"]["records"] == 7
    assert fleet.aggregate_health({0: base})["usage"] is None
    # fleet_metrics surfaces the same block for `manager metrics`
    fm = fleet.fleet_metrics({0: doc0, 1: doc1})
    assert fm["usage"]["zeta"]["records"] == 7


def test_merged_prometheus_max_merges_per_tenant_burn(ctx):
    """Fleet prometheus merge: labelled counters SUM per (tenant, model)
    series; serving_slo_burn_rate MAX-merges PER TENANT — the fleet's
    view of a tenant is its worst replica."""
    r1, r2 = MetricsRegistry(), MetricsRegistry()
    for reg, n, burn in ((r1, 3, 0.5), (r2, 4, 2.5)):
        _records_counter(reg).labels(tenant="acme", model="default").inc(n)
        reg.gauge("serving_slo_burn_rate", labels=("tenant",)) \
            .labels(tenant="acme").set(burn)
    merged = fleet.merge_prometheus([r1.to_prometheus(),
                                     r2.to_prometheus()])
    assert ('serving_records_total{tenant="acme",model="default"} 7'
            in merged)
    assert 'serving_slo_burn_rate{tenant="acme"} 2.5' in merged


# -- real-process acceptance ---------------------------------------------------

def _http_json(url, data=None, headers=None, timeout=10):
    import urllib.error
    import urllib.request
    req = urllib.request.Request(url, data=data, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.mark.slow
@pytest.mark.timeout(420)
def test_two_replica_lb_usage_rollup_survives_stop(tmp_path):
    """ISSUE 19 acceptance: 2 real replicas behind the LB, two tenants
    pushing through the front door with X-Tenant headers -> the labelled
    attribution crosses LB -> gateway -> engine -> journal, `manager
    usage` matches the client's own counts EXACTLY, and the journal (plus
    the rollup) survives `manager stop`."""
    import socket

    from test_serving_lifecycle import _write_zoo_model

    weights, topo = _write_zoo_model(tmp_path)
    qdir = tmp_path / "queue"
    ports = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    port, lb_port = ports
    cfg = tmp_path / "config.yaml"
    cfg.write_text(
        f"model:\n  path: {weights}\n  type: zoo\n  topology: {topo}\n"
        f"data:\n  src: file:{qdir}\n"
        "params:\n"
        "  batch_size: 4\n"
        f"  http_port: {port}\n"
        "  drain_s: 2\n"
        "  compile_cache_dir: off\n")
    pidfile = str(tmp_path / "cs.pid")
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    mgr = [sys.executable, "-m", "analytics_zoo_tpu.serving.manager"]
    log = str(tmp_path / "supervisor.log")
    log_f = open(log, "w")
    proc = subprocess.Popen(
        mgr + ["start", "-c", str(cfg), "--pidfile", pidfile,
               "--replicas", "2", "--lb-port", str(lb_port),
               "--foreground", "--no-prewarm"],
        cwd=str(tmp_path), env=env, stdout=log_f, stderr=subprocess.STDOUT)
    counts = {"acme": 12, "zeta": 8}
    try:
        deadline = time.monotonic() + 180
        ready = set()
        while len(ready) < 2 and time.monotonic() < deadline:
            assert proc.poll() is None, open(log).read()[-4000:]
            for i in range(2):
                try:
                    code, _ = _http_json(
                        f"http://127.0.0.1:{port + i}/readyz", timeout=2)
                    if code == 200:
                        ready.add(i)
                except Exception:  # noqa: BLE001 — still booting
                    pass
            time.sleep(0.3)
        assert ready == {0, 1}, open(log).read()[-4000:]

        def push(tenant, n, failures):
            for i in range(n):
                uri = f"{tenant}-{i}"
                body = json.dumps({"uri": uri, "data": [0.1] * 4}).encode()
                code, ack = _http_json(
                    f"http://127.0.0.1:{lb_port}/v1/enqueue", data=body,
                    headers={"Content-Type": "application/json",
                             "X-Tenant": tenant,
                             "X-Priority": "interactive"})
                if code != 200:
                    failures.append((uri, code, ack))
                    continue
                code, res = _http_json(
                    f"http://127.0.0.1:{lb_port}/v1/result/{uri}"
                    "?timeout_s=30", timeout=40)
                if code != 200 or "value" not in res:
                    failures.append((uri, code, res))
                elif res.get("tenant") != tenant:
                    failures.append((uri, "tenant", res.get("tenant")))

        failures = []
        threads = [threading.Thread(target=push, args=(t, n, failures))
                   for t, n in counts.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert failures == [], failures[:5]
        time.sleep(1.5)        # one journal drain interval past the last ack
    finally:
        subprocess.run(mgr + ["stop", "--pidfile", pidfile],
                       cwd=str(tmp_path), env=env, capture_output=True)
        try:
            proc.wait(timeout=90)
        except subprocess.TimeoutExpired:
            proc.kill()
        log_f.close()
    # the deployment is DOWN; the journal is not
    spools = tracecollect.find_usage_spools(pidfile)
    assert spools, os.listdir(str(tmp_path))
    r = subprocess.run(mgr + ["usage", "--pidfile", pidfile, "--json"],
                       cwd=str(tmp_path), env=env,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    # billing-grade: the rollup matches the client's own counts EXACTLY
    for tenant, n in counts.items():
        assert doc["usage"][tenant]["records"] == n, doc["usage"]
        assert doc["usage"][tenant]["device_s"] > 0, doc["usage"]
    assert UNKNOWN_TENANT not in doc["usage"], doc["usage"]
