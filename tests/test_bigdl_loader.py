"""BigDL serialized `.model` reader (round 5, VERDICT r4 next #9):
dependency-free protobuf codec validated against the reference's COMMITTED
artifact (zoo/src/test/resources/models/bigdl/bigdl_lenet.model) — the
published-zoo format Net.loadBigDL consumed (Net.scala:157-277).

Skipped when the reference checkout isn't present.
"""

import os

import numpy as np
import pytest

LENET = ("/root/reference/zoo/src/test/resources/models/bigdl/"
         "bigdl_lenet.model")

pytestmark = pytest.mark.skipif(not os.path.exists(LENET),
                                reason="reference artifact not available")


def test_parse_module_tree_and_weights():
    from analytics_zoo_tpu.interop.bigdl_loader import load_bigdl

    root = load_bigdl(LENET)
    assert root.module_type.endswith("StaticGraph")
    mods = {m.name: m for m in root.sub_modules}
    assert set(mods) == {"reshape1", "conv1_5x5", "tanh1", "pool1", "tanh2",
                         "conv2_5x5", "pool3", "reshape2", "fc1", "tanh3",
                         "fc2", "logSoftMax"}
    # weights materialize through the deduped global_storage table with the
    # documented shapes (BigDL conv (group, out/g, in/g, kH, kW))
    assert mods["conv1_5x5"].weight.shape == (1, 6, 1, 5, 5)
    assert mods["conv2_5x5"].weight.shape == (1, 12, 6, 5, 5)
    assert mods["fc1"].weight.shape == (100, 192)
    assert mods["fc2"].weight.shape == (5, 100)
    assert mods["fc2"].bias.shape == (5,)
    # real trained values, not zeros
    assert float(np.abs(mods["fc1"].weight).mean()) > 1e-4


def test_convert_to_native_and_forward():
    from analytics_zoo_tpu.interop.bigdl_loader import (bigdl_to_native,
                                                        load_bigdl)

    model = bigdl_to_native(LENET, (1, 28, 28))
    x = np.random.default_rng(0).normal(size=(2, 1, 28, 28)) \
        .astype(np.float32)
    y = model.predict(x, batch_size=2)
    assert y.shape == (2, 5)
    # LogSoftMax output: probabilities sum to 1
    np.testing.assert_allclose(np.exp(y).sum(-1), 1.0, rtol=1e-5)
    # the artifact's weights are attached (fc2 row 0 matches the parse)
    root = load_bigdl(LENET)
    fc2 = {m.name: m for m in root.sub_modules}["fc2"]
    got = np.asarray(model.get_weights()["bd_fc2"]["W"])
    np.testing.assert_allclose(got, fc2.weight.T, rtol=1e-6)


def test_net_facade():
    from analytics_zoo_tpu.nn.net import Net

    model = Net.load_bigdl(LENET, (1, 28, 28))
    assert model.predict(np.zeros((1, 1, 28, 28), np.float32),
                         batch_size=1).shape == (1, 5)
