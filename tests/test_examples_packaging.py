"""Packaging + runnable examples + serving hardening (VERDICT r2 #9).

Examples run as in-process smoke tests (the reference's
run-example-tests*.sh pattern); the pipelined serving loop is exercised
end-to-end through the client queue surface; the Redis queue test is
skip-guarded on a reachable server.
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(name, argv):
    """Run an example's main(argv) in a FRESH subprocess (round 5): the
    examples exercise long in-process train loops, and a native-level crash
    (XLA CPU abort under host oversubscription was observed) must fail ONE
    test, not kill the whole pytest interpreter.  The child returns main()'s
    dict as a tagged JSON line."""
    import json
    import subprocess

    path = os.path.join(REPO, "examples", name)
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import importlib.util, json\n"
        f"spec = importlib.util.spec_from_file_location('example', {path!r})\n"
        "mod = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(mod)\n"
        f"out = mod.main({argv!r})\n"
        "print('EXAMPLE_JSON:' + json.dumps(out, default=float))\n")
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, cwd=REPO, env=env, timeout=900)
    assert r.returncode == 0, (f"example {name} failed:\n"
                               f"stdout:\n{r.stdout[-1500:]}\n"
                               f"stderr:\n{r.stderr[-2500:]}")
    for line in reversed(r.stdout.strip().splitlines()):
        if line.startswith("EXAMPLE_JSON:"):
            return json.loads(line[len("EXAMPLE_JSON:"):])
    raise AssertionError(f"example {name} produced no result line:\n"
                         f"{r.stdout[-2000:]}")


def test_ncf_example_quick():
    out = _run_example("ncf_train.py", ["--quick"])
    assert out["hr_at_10"] > 0.15          # well above untrained baseline
    assert out["eval_users"] == 400


def test_serving_roundtrip_example():
    out = _run_example("serving_roundtrip.py", ["--n", "32"])
    assert out["ok"] and out["completed"] == 32


def test_image_classification_example_quick():
    out = _run_example("image_classification.py", ["--quick"])
    assert out["predict_shape"] == [64, 4]
    assert np.isfinite(out["train_accuracy"])


def test_pipelined_serving_overlaps_and_backpressures(ctx):
    """start() runs a preprocess thread + predict thread with a bounded
    staging buffer; results must flow and the buffer must never exceed
    pipeline_depth."""
    from analytics_zoo_tpu.inference.inference_model import InferenceModel
    from analytics_zoo_tpu.nn import Sequential
    from analytics_zoo_tpu.nn.layers import Dense
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
    from analytics_zoo_tpu.serving.engine import ClusterServing, ServingParams
    from analytics_zoo_tpu.serving.queues import InProcQueue

    model = Sequential()
    model.add(Dense(4, input_shape=(3,), activation="softmax"))
    model.init_weights()
    im = InferenceModel().do_load_model(model, model._params, model._state)
    q = InProcQueue()
    serving = ClusterServing(im, q, params=ServingParams(
        batch_size=4, pipeline_depth=2))
    serving.start()
    assert serving._pre_thread.is_alive() and serving._thread.is_alive()

    cin, cout = InputQueue(q), OutputQueue(q)
    g = np.random.default_rng(0)
    ids = [cin.enqueue_tensor(f"u{i}", g.normal(size=(3,)).astype(np.float32))
           for i in range(40)]
    got = {}
    deadline = time.time() + 20
    while len(got) < len(ids) and time.time() < deadline:
        for rid in ids:
            if rid not in got:
                r = cout.query(rid)
                if r is not None:
                    got[rid] = r
        time.sleep(0.01)
    serving.shutdown()
    assert len(got) == len(ids)
    assert serving._staged.maxsize == 2


def test_result_write_retries_with_backoff(ctx):
    from analytics_zoo_tpu.inference.inference_model import InferenceModel
    from analytics_zoo_tpu.nn import Sequential
    from analytics_zoo_tpu.nn.layers import Dense
    from analytics_zoo_tpu.serving.engine import ClusterServing, ServingParams
    from analytics_zoo_tpu.serving.queues import InProcQueue

    model = Sequential()
    model.add(Dense(2, input_shape=(3,), activation="softmax"))
    model.init_weights()
    im = InferenceModel().do_load_model(model, model._params, model._state)

    class Flaky(InProcQueue):
        # transient result-store outage on the write hot path: the engine
        # writes through the batched put_results (PR 3) and falls back to
        # per-record put_result, so both draw from one failure budget
        def __init__(self):
            super().__init__()
            self.failures = 3

        def _maybe_fail(self):
            if self.failures > 0:
                self.failures -= 1
                raise ConnectionError("redis OOM")   # ClusterServing.scala:276

        def put_results(self, pairs):
            self._maybe_fail()
            return super().put_results(pairs)

        def put_result(self, key, value):
            self._maybe_fail()
            return super().put_result(key, value)

    q = Flaky()
    serving = ClusterServing(im, q, params=ServingParams(
        batch_size=2, write_retries=5, write_backoff_s=0.001))
    q.xadd({"uri": "a", "data": [1.0, 2.0, 3.0], "shape": [3]})
    assert serving.serve_once() == 1
    assert q.failures == 0                      # retried through the failures
    assert q.get_result("1") is not None or q.result_count() == 1

    # exhausted retries no longer kill the worker (PR 1 resilience): the
    # record is quarantined to the dead-letter channel with a visible error
    # result instead of the exception escaping the serve loop
    q2 = Flaky()
    q2.failures = 99
    serving2 = ClusterServing(im, q2, params=ServingParams(
        batch_size=2, write_retries=2, write_backoff_s=0.001))
    q2.xadd({"uri": "b", "data": [1.0, 2.0, 3.0], "shape": [3]})
    assert serving2.serve_once() == 0
    dead = q2.dead_letters()
    assert [d["uri"] for d in dead] == ["b"]
    assert "error" in q2.get_result("b")


def _redis_available():
    try:
        import redis
        r = redis.Redis(socket_connect_timeout=0.3)
        r.ping()
        return True
    except Exception:
        return False


@pytest.mark.skipif(not _redis_available(),
                    reason="no reachable redis server")
def test_redis_queue_roundtrip(ctx):
    from analytics_zoo_tpu.serving.queues import RedisQueue

    q = RedisQueue(stream=f"zoo_test_{os.getpid()}")
    rid = q.xadd({"uri": "x", "data": [1.0], "shape": [1]})
    batch = q.read_batch(4, timeout_s=1.0)
    assert any(r == rid for r, _ in batch)
    q.put_result(rid, {"value": [[0, 1.0]]})
    assert q.get_result(rid)["value"] == [[0, 1.0]]


def test_editable_install_metadata():
    """pyproject.toml produces an installable distribution
    (pip install -e . executed during the build; skip when absent)."""
    try:
        import importlib.metadata as md
        version = md.version("analytics-zoo-tpu")
    except Exception:
        pytest.skip("analytics-zoo-tpu not pip-installed in this env")
    assert version == "0.3.0"
