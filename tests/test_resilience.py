"""Resilience library (PR 1 tentpole): RetryPolicy backoff/deadline,
CircuitBreaker trip/half-open/close, SupervisedThread crash-restart-cap, and
the deterministic FaultInjector that drives all of it.  No test sleeps longer
than ~0.2 s — clocks and sleeps are injectable."""

import threading
import time

import pytest

from analytics_zoo_tpu.common.resilience import (CircuitBreaker,
                                                 CircuitBreakerOpen,
                                                 Deadline, RetryPolicy,
                                                 RetryExhausted,
                                                 SupervisedThread)
from analytics_zoo_tpu.utils.chaos import FaultInjector, InjectedFault

# chaos-driven unit tests: generous per-test cap (conftest SIGALRM guard) so
# a wedged supervised thread can't stall the tier-1 run
pytestmark = pytest.mark.timeout(60)


# -- RetryPolicy ---------------------------------------------------------------

def test_retry_recovers_after_transient_failures():
    inj = FaultInjector().fail("op", times=3)
    sleeps = []
    policy = RetryPolicy(max_retries=5, base_delay_s=0.01,
                         sleep=sleeps.append)
    calls = []

    def op():
        inj.maybe_fail("op")
        calls.append(1)
        return "ok"

    assert policy.call(op) == "ok"
    assert inj.count("op") == 4 and len(calls) == 1
    # exact deterministic backoff schedule (no jitter)
    assert sleeps == [0.01, 0.02, 0.04]


def test_retry_exhaustion_chains_original_error():
    inj = FaultInjector().fail("op", times=99)
    policy = RetryPolicy(max_retries=2, base_delay_s=0.001)
    with pytest.raises(RetryExhausted) as ei:
        policy.call(lambda: inj.maybe_fail("op"))
    assert isinstance(ei.value.__cause__, InjectedFault)
    assert inj.count("op") == 3            # initial try + 2 retries


def test_retry_deadline_cuts_schedule_short():
    inj = FaultInjector().fail("op", times=99)
    t = [0.0]
    sleeps = []

    def fake_sleep(d):
        sleeps.append(d)
        t[0] += d

    policy = RetryPolicy(max_retries=50, base_delay_s=0.1, multiplier=1.0,
                         deadline_s=0.35, sleep=fake_sleep,
                         clock=lambda: t[0])
    with pytest.raises(RetryExhausted, match="deadline"):
        policy.call(lambda: inj.maybe_fail("op"))
    # 0.1-delay retries fit 3 times under a 0.35 s deadline
    assert len(sleeps) == 3


def test_retry_jitter_is_deterministic_and_bounded():
    p = RetryPolicy(base_delay_s=0.1, jitter=0.5)
    d0, d1 = p.delay(0), p.delay(1)
    assert d0 == p.delay(0)                # same attempt -> same delay
    assert 0.1 <= d0 <= 0.15 and 0.2 <= d1 <= 0.3


def test_deadline_remaining():
    t = [0.0]
    d = Deadline(1.0, clock=lambda: t[0])
    assert d.remaining() == 1.0 and not d.expired()
    t[0] = 1.5
    assert d.expired()
    assert Deadline(None).remaining() == float("inf")


def test_wait_until_polls_to_timeout():
    from analytics_zoo_tpu.common.resilience import wait_until

    t = [0.0]
    slept = []

    def fake_sleep(s):
        slept.append(s)
        t[0] += s

    # flips true after 0.05s of fake time
    assert wait_until(lambda: t[0] >= 0.05, timeout_s=1.0, poll_s=0.02,
                      sleep=fake_sleep, clock=lambda: t[0]) is True
    assert t[0] < 0.1 and slept
    # never flips: returns False once the budget elapses, no real waiting
    t[0] = 0.0
    assert wait_until(lambda: False, timeout_s=0.1, poll_s=0.02,
                      sleep=fake_sleep, clock=lambda: t[0]) is False
    assert t[0] >= 0.1


# -- CircuitBreaker ------------------------------------------------------------

def test_breaker_trips_fails_fast_and_half_opens():
    t = [0.0]
    br = CircuitBreaker(failure_threshold=3, cooldown_s=1.0,
                        clock=lambda: t[0])
    inj = FaultInjector().fail("write", times=3)

    for _ in range(3):
        with pytest.raises(InjectedFault):
            br.call(lambda: inj.maybe_fail("write"))
    assert br.state == CircuitBreaker.OPEN and br.trip_count == 1

    # OPEN: calls fail fast WITHOUT touching the backend
    with pytest.raises(CircuitBreakerOpen):
        br.call(lambda: inj.maybe_fail("write"))
    assert inj.count("write") == 3

    # cooldown elapses -> HALF_OPEN probe; success closes the breaker
    t[0] = 1.5
    assert br.state == CircuitBreaker.HALF_OPEN
    assert br.call(lambda: "ok") == "ok"
    assert br.state == CircuitBreaker.CLOSED


def test_breaker_reopens_when_probe_fails():
    t = [0.0]
    br = CircuitBreaker(failure_threshold=1, cooldown_s=1.0,
                        clock=lambda: t[0])
    with pytest.raises(ValueError):
        br.call(lambda: (_ for _ in ()).throw(ValueError("x")))
    t[0] = 1.1
    with pytest.raises(ValueError):        # the half-open probe fails
        br.call(lambda: (_ for _ in ()).throw(ValueError("y")))
    assert br.state == CircuitBreaker.OPEN  # fresh cooldown window
    with pytest.raises(CircuitBreakerOpen):
        br.call(lambda: "never")


def test_breaker_success_resets_consecutive_count():
    br = CircuitBreaker(failure_threshold=2)
    with pytest.raises(ValueError):
        br.call(lambda: (_ for _ in ()).throw(ValueError("x")))
    br.call(lambda: "ok")                  # resets the streak
    with pytest.raises(ValueError):
        br.call(lambda: (_ for _ in ()).throw(ValueError("x")))
    assert br.state == CircuitBreaker.CLOSED
    assert br.health()["consecutive_failures"] == 1


# -- SupervisedThread ----------------------------------------------------------

def test_supervised_thread_restarts_after_crash():
    inj = FaultInjector().fail("worker", times=2)
    stop = threading.Event()
    done = threading.Event()

    def worker():
        inj.maybe_fail("worker")           # crashes the first 2 incarnations
        done.set()
        stop.wait(5)

    sup = SupervisedThread(worker, name="w", max_restarts=5,
                           backoff_s=0.005, stop_event=stop).start()
    assert done.wait(5)
    h = sup.health()
    assert h["restart_count"] == 2
    assert h["state"] == SupervisedThread.RUNNING and h["alive"]
    assert "InjectedFault" in h["last_error"]
    sup.stop(timeout=2)
    assert sup.health()["state"] == SupervisedThread.STOPPED
    assert not sup.is_alive()


def test_supervised_thread_gives_up_at_restart_cap():
    inj = FaultInjector().fail("worker", times=99)
    crashes = []
    sup = SupervisedThread(lambda: inj.maybe_fail("worker"), name="w",
                           max_restarts=3, backoff_s=0.001,
                           on_crash=crashes.append).start()
    sup.join(timeout=5)
    h = sup.health()
    assert h["state"] == SupervisedThread.FAILED and not h["alive"]
    assert h["restart_count"] == 4         # initial run + 3 restarts
    assert len(crashes) == 4


def test_supervised_thread_streak_resets_after_healthy_run():
    """The restart cap bounds CONSECUTIVE crash-loops: an incarnation that
    ran healthy for healthy_after_s resets the streak, so transient faults
    spread over a long serving lifetime never exhaust the budget."""
    t = [0.0]

    def clock():
        t[0] += 100.0                      # every incarnation looks long-lived
        return t[0]

    inj = FaultInjector().fail("worker", times=4)
    stop = threading.Event()
    done = threading.Event()

    def worker():
        inj.maybe_fail("worker")
        done.set()
        stop.wait(5)

    sup = SupervisedThread(worker, name="w", max_restarts=1, backoff_s=0.001,
                           healthy_after_s=30.0, stop_event=stop,
                           clock=clock).start()
    assert done.wait(5)                    # survived 4 faults with cap=1
    h = sup.health()
    assert h["restart_count"] == 4 and h["crash_streak"] == 1
    assert h["state"] == SupervisedThread.RUNNING
    sup.stop(timeout=2)


def test_supervised_thread_heartbeat_and_clean_return():
    clock = [100.0]
    sup = SupervisedThread(lambda: None, name="w", clock=lambda: clock[0])

    def worker():
        sup.heartbeat()

    sup.target = worker
    sup.start()
    sup.join(timeout=2)
    h = sup.health()
    assert h["state"] == SupervisedThread.STOPPED
    assert h["last_progress"] == 100.0 and h["restart_count"] == 0


# -- FaultInjector -------------------------------------------------------------

def test_injector_schedules_by_index_and_predicate():
    inj = FaultInjector()
    inj.fail_at("pre", indices=[1, 3])
    inj.fail_when("predict", lambda ctx: ctx.get("rid") == "poison")

    outcomes = []
    for i in range(5):
        try:
            inj.maybe_fail("pre")
            outcomes.append("ok")
        except InjectedFault:
            outcomes.append("boom")
    assert outcomes == ["ok", "boom", "ok", "boom", "ok"]
    assert inj.fired == ["pre#1", "pre#3"]

    inj.maybe_fail("predict", rid="fine")
    with pytest.raises(InjectedFault):
        inj.maybe_fail("predict", rid="poison")


def test_injector_wrap_and_custom_exception():
    inj = FaultInjector().fail("q", times=1, exc=ConnectionError,
                               message="redis down")
    calls = []
    wrapped = inj.wrap("q", lambda x: calls.append(x) or x)
    with pytest.raises(ConnectionError, match="redis down"):
        wrapped(1)
    assert wrapped(2) == 2
    assert calls == [2] and inj.count("q") == 2
    inj.reset("q")
    assert inj.count("q") == 0


def test_injector_thread_safety_counts():
    inj = FaultInjector()
    n_threads, per = 8, 200

    def hammer():
        for _ in range(per):
            inj.maybe_fail("site")

    ts = [threading.Thread(target=hammer) for _ in range(n_threads)]
    t0 = time.time()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert inj.count("site") == n_threads * per
    assert time.time() - t0 < 5
