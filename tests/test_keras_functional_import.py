"""Functional tf.keras graph import (round 5, VERDICT r4 missing #2 +
weak #8): topological-walk conversion of functional Models — merges, skip
connections, multi-branch graphs, depthwise/separable convs, LayerNorm —
and the EXACT GRU reset_after import.  Every case is a differential oracle:
tf output vs native output to 1e-4.

Reference: tf_optimizer.py:578-667 `TFOptimizer.from_keras` breadth.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
from tensorflow import keras  # noqa: E402

from analytics_zoo_tpu.interop.keras_import import from_tf_keras  # noqa: E402


def _check(tf_model, x, atol=1e-4, multi_in=False):
    native = from_tf_keras(tf_model)
    want = tf_model(x if not multi_in else [np.asarray(a) for a in x])
    if isinstance(want, (list, tuple)):
        want = [np.asarray(w) for w in want]
    else:
        want = [np.asarray(want)]
    got = native.predict(x, batch_size=64)
    if not isinstance(got, list):
        got = [got]
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=atol, atol=atol)
    return native


def test_functional_resnet_style_block(rng):
    """Conv + BN + ReLU with an Add skip — the ResNet motif the VERDICT names
    as the acceptance case."""
    inp = keras.Input((16, 16, 8))
    h = keras.layers.Conv2D(8, 3, padding="same", name="c1")(inp)
    h = keras.layers.BatchNormalization(name="bn1")(h)
    h = keras.layers.Activation("relu")(h)
    h = keras.layers.Conv2D(8, 3, padding="same", name="c2")(h)
    h = keras.layers.Add(name="skip")([h, inp])
    h = keras.layers.Activation("relu")(h)
    h = keras.layers.GlobalAveragePooling2D()(h)
    out = keras.layers.Dense(4, activation="softmax")(h)
    m = keras.Model(inp, out)
    # make BN stats non-trivial
    m(rng.normal(size=(32, 16, 16, 8)).astype(np.float32), training=True)
    x = rng.normal(size=(4, 16, 16, 8)).astype(np.float32)
    _check(m, x)


def test_functional_multi_branch_concat(rng):
    inp = keras.Input((12,))
    a = keras.layers.Dense(6, activation="relu")(inp)
    b = keras.layers.Dense(6, activation="tanh")(inp)
    c = keras.layers.Concatenate(axis=-1)([a, b])
    d = keras.layers.Multiply()([a, b])
    out = keras.layers.Concatenate(axis=-1)([c, d])
    m = keras.Model(inp, out)
    _check(m, rng.normal(size=(5, 12)).astype(np.float32))


def test_functional_multi_input_multi_output(rng):
    i1 = keras.Input((8,), name="in1")
    i2 = keras.Input((8,), name="in2")
    s = keras.layers.Subtract()([i1, i2])
    m1 = keras.layers.Maximum()([i1, i2])
    o1 = keras.layers.Dense(3, name="o1")(s)
    o2 = keras.layers.Dense(2, name="o2")(m1)
    m = keras.Model([i1, i2], [o1, o2])
    x = [rng.normal(size=(6, 8)).astype(np.float32),
         rng.normal(size=(6, 8)).astype(np.float32)]
    _check(m, x, multi_in=True)


def test_functional_shared_layer(rng):
    """One Dense applied to two inputs: native params are shared by layer
    name, so both call sites must use the same weights."""
    i1 = keras.Input((8,))
    i2 = keras.Input((8,))
    shared = keras.layers.Dense(4, name="shared_d")
    out = keras.layers.Add()([shared(i1), shared(i2)])
    m = keras.Model([i1, i2], out)
    x = [rng.normal(size=(3, 8)).astype(np.float32),
         rng.normal(size=(3, 8)).astype(np.float32)]
    _check(m, x, multi_in=True)


def test_depthwise_and_separable_import(rng):
    inp = keras.Input((10, 10, 6))
    h = keras.layers.DepthwiseConv2D(3, padding="same", depth_multiplier=2,
                                     name="dw")(inp)
    h = keras.layers.SeparableConv2D(8, 3, padding="valid", name="sep")(h)
    m = keras.Model(inp, h)
    _check(m, rng.normal(size=(2, 10, 10, 6)).astype(np.float32))


def test_layernorm_import(rng):
    inp = keras.Input((7, 12))
    h = keras.layers.LayerNormalization(name="ln")(inp)
    out = keras.layers.Dense(5)(h)
    m = keras.Model(inp, out)
    # non-trivial gamma/beta
    m.get_layer("ln").set_weights([
        rng.normal(size=(12,)).astype(np.float32) + 1.0,
        rng.normal(size=(12,)).astype(np.float32)])
    _check(m, rng.normal(size=(3, 7, 12)).astype(np.float32))


@pytest.mark.parametrize("reset_after", [False, True])
def test_gru_import_exact(rng, reset_after):
    """reset_after=True must import EXACTLY (native reset_after cell, round
    5) — the r4 bias-collapse approximation was not exact because
    (r*h)@U != r*(h@U)."""
    m = keras.Sequential([
        keras.Input((9, 5)),
        keras.layers.GRU(7, reset_after=reset_after, activation="tanh",
                         recurrent_activation="sigmoid",
                         return_sequences=True),
    ])
    # randomize the bias so the recurrent bias is NONZERO (the hard case)
    wts = m.layers[0].get_weights()
    wts[-1] = rng.normal(size=wts[-1].shape).astype(np.float32)
    m.layers[0].set_weights(wts)
    x = rng.normal(size=(4, 9, 5)).astype(np.float32)
    _check(m, x, atol=2e-4)


def test_conv2d_transpose_import(rng):
    m = keras.Sequential([
        keras.Input((6, 6, 4)),
        keras.layers.Conv2DTranspose(8, 3, strides=2, padding="same"),
    ])
    _check(m, rng.normal(size=(2, 6, 6, 4)).astype(np.float32))
