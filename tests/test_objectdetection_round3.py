"""Round-3 object-detection widening (VERDICT weak #6): VOC/COCO parsing,
PascalVocEvaluator protocols, the pretrained-config registry, and the
ObjectDetector facade save/load round trip."""

import json

import numpy as np
import pytest

from analytics_zoo_tpu.models.objectdetection import (
    VOC_CLASSES, ObjectDetectionConfig, ObjectDetector, PascalVocEvaluator,
    average_precision, average_precision_07, load_coco_annotations,
    parse_voc_annotation)


def test_parse_voc_annotation(tmp_path):
    xml = tmp_path / "img1.xml"
    xml.write_text("""
    <annotation>
      <size><width>200</width><height>100</height><depth>3</depth></size>
      <object><name>dog</name><difficult>0</difficult>
        <bndbox><xmin>20</xmin><ymin>10</ymin><xmax>120</xmax><ymax>60</ymax></bndbox>
      </object>
      <object><name>cat</name><difficult>1</difficult>
        <bndbox><xmin>0</xmin><ymin>0</ymin><xmax>50</xmax><ymax>50</ymax></bndbox>
      </object>
      <object><name>unknownthing</name>
        <bndbox><xmin>1</xmin><ymin>1</ymin><xmax>2</xmax><ymax>2</ymax></bndbox>
      </object>
    </annotation>""")
    boxes, labels, difficult = parse_voc_annotation(str(xml))
    assert boxes.shape == (2, 4)
    np.testing.assert_allclose(boxes[0], [0.1, 0.1, 0.6, 0.6])
    assert labels[0] == VOC_CLASSES.index("dog") + 1
    assert labels[1] == VOC_CLASSES.index("cat") + 1
    assert difficult.tolist() == [0, 1]


def test_load_coco_annotations(tmp_path):
    coco = {
        "images": [{"id": 1, "width": 100, "height": 50}],
        "categories": [{"id": 7, "name": "dog"}, {"id": 99, "name": "cat"}],
        "annotations": [
            {"image_id": 1, "category_id": 7, "bbox": [10, 5, 20, 10]},
            {"image_id": 1, "category_id": 99, "bbox": [0, 0, 50, 25]},
        ]}
    p = tmp_path / "instances.json"
    p.write_text(json.dumps(coco))
    gt = load_coco_annotations(str(p))
    boxes, labels = gt[1]
    np.testing.assert_allclose(boxes[0], [0.1, 0.1, 0.3, 0.3])
    assert labels.tolist() == [1, 2]          # dense remap by category id


def test_evaluator_protocols():
    gt = [(np.asarray([[0.1, 0.1, 0.4, 0.4], [0.6, 0.6, 0.9, 0.9]]),
           np.asarray([1, 2]))]
    dets = [[(1, 0.9, np.asarray([0.1, 0.1, 0.4, 0.4])),   # perfect match
             (2, 0.8, np.asarray([0.0, 0.0, 0.1, 0.1]))]]  # miss
    ev = PascalVocEvaluator(num_classes=3)
    res = ev.evaluate(dets, gt)
    assert res[1] > 0.95 and res[2] == 0.0
    assert 0.4 < res["mAP"] < 0.6
    ev07 = PascalVocEvaluator(num_classes=3, use_07_metric=True)
    res07 = ev07.evaluate(dets, gt)
    assert res07[1] > 0.95
    # identical perfect/miss structure: protocols agree at the extremes
    assert abs(res07["mAP"] - res["mAP"]) < 0.05


def test_config_registry_and_detector_roundtrip(tmp_path, ctx):
    cfg = ObjectDetectionConfig.get("ssd-compact-small-288x288")
    assert cfg["class_num"] == 21 and cfg["label_map"][0] == "__background__"
    # published names resolve to the REAL architecture (round 5)
    assert ObjectDetectionConfig.get("ssd-vgg16-300x300")["arch"] == "vgg16"
    with pytest.raises(KeyError, match="unknown"):
        ObjectDetectionConfig.get("yolo-9000")

    ObjectDetectionConfig.register("ssd-tiny-test", class_num=4,
                                   image_size=32, base_filters=8,
                                   label_map=("bg", "a", "b", "c"))
    det = ObjectDetector("ssd-tiny-test")
    g = np.random.default_rng(0)
    imgs = g.integers(0, 255, (2, 32, 32, 3)).astype(np.float32)
    out = det.predict(imgs, score_threshold=0.05)
    assert len(out) == 2
    for dets in out:
        for (c, s, box) in dets:
            assert 1 <= c < 4 and 0 <= s <= 1 and box.shape == (4,)

    w = tmp_path / "ssd.npz"
    det.save(str(w))
    det2 = ObjectDetector.load_model("ssd-tiny-test", str(w))
    out2 = det2.predict(imgs, score_threshold=0.05)
    assert len(out2) == 2 and len(out2[0]) == len(out[0])


def test_detector_predict_image_set(ctx):
    from analytics_zoo_tpu.feature.image import ImageSet

    ObjectDetectionConfig.register("ssd-tiny-test2", class_num=3,
                                   image_size=32, base_filters=8)
    det = ObjectDetector("ssd-tiny-test2")
    g = np.random.default_rng(1)
    iset = ImageSet.from_arrays(
        [g.integers(0, 255, (48, 40, 3)).astype(np.uint8) for _ in range(3)])
    out = det.predict_image_set(iset, score_threshold=0.05)
    assert len(out) == 3


def test_evaluator_consumes_voc_3tuples_and_ignores_difficult():
    """VOC protocol: difficult boxes leave the GT count and matching them is
    neither TP nor FP; parse_voc_annotation's 3-tuple feeds evaluate directly."""
    gt = [(np.asarray([[0.1, 0.1, 0.4, 0.4], [0.6, 0.6, 0.9, 0.9]]),
           np.asarray([1, 1]), np.asarray([0, 1]))]     # second is difficult
    dets = [[(1, 0.9, np.asarray([0.1, 0.1, 0.4, 0.4])),   # TP on easy box
             (1, 0.8, np.asarray([0.6, 0.6, 0.9, 0.9]))]]  # matches difficult
    res = PascalVocEvaluator(num_classes=2).evaluate(dets, gt)
    # 1 easy GT, 1 TP, difficult match ignored -> AP = 1.0
    assert res[1] > 0.99, res
    # without the difficult flag the same detections give a perfect 2/2 too,
    # but marking the first det as a miss shows the FP path still works
    dets_fp = [[(1, 0.9, np.asarray([0.1, 0.1, 0.4, 0.4])),
                (1, 0.85, np.asarray([0.0, 0.5, 0.1, 0.6]))]]  # plain FP
    res_fp = PascalVocEvaluator(num_classes=2).evaluate(dets_fp, gt)
    assert res_fp[1] > 0.9   # FP ranked below the TP: precision@recall=1 is 1
