"""Multi-host (multi-process) training test (VERDICT r4 #2).

Spawns 2 worker processes, each with 4 virtual CPU devices, bootstrapped via
``jax.distributed.initialize`` through ``ZooConf.coordinator_address``.  The
global mesh is 8 devices; each process feeds only its partition; the global
batch is assembled with ``jax.make_array_from_process_local_data``.  Training
losses must match a single-process 8-device run on the same data exactly
(pure f32, shuffle off) — the reference's claim to fame is this kind of
scale-out equivalence (wp-bigdl.md:160-164).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(coord, nprocs, pid, n_rows=None):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    if n_rows is not None:
        env["ZOO_TEST_N"] = str(n_rows)
    return subprocess.Popen(
        [sys.executable, WORKER, coord, str(nprocs), str(pid)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, env=env)


def _run_workers(nprocs, n_rows=None):
    coord = f"127.0.0.1:{_free_port()}"
    procs = [_spawn(coord, nprocs, pid, n_rows) for pid in range(nprocs)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))
    return outs


def _single_process_reference():
    """In-process 8-device run over data REORDERED to the multi-host global
    batch layout: global batch k = [proc0 rows 16k:16k+16, proc1 rows
    16k:16k+16] — same global arrays, same mesh size, so the losses must
    match the 2-process run exactly."""
    import sys
    sys.path.insert(0, os.path.dirname(WORKER))
    from multihost_worker import make_data
    from analytics_zoo_tpu.common.context import get_context
    from analytics_zoo_tpu.estimator.estimator import Estimator
    from analytics_zoo_tpu.nn import Sequential
    from analytics_zoo_tpu.nn.layers import Dense

    x, y = make_data()
    n, half, fb = len(x), len(x) // 2, 16
    order = np.concatenate([
        np.concatenate([np.arange(k * fb, (k + 1) * fb),
                        half + np.arange(k * fb, (k + 1) * fb)])
        for k in range(half // fb)])
    x, y = x[order], y[order]

    # reuse (and reseed) the session context — init_context here would
    # REPLACE the process-global ctx and leave other tests' fixtures stale
    ctx = get_context()
    ctx.set_seed(42)
    model = Sequential()
    model.add(Dense(16, activation="tanh", input_shape=(x.shape[1],)))
    model.add(Dense(1, activation="sigmoid"))
    est = Estimator(model, optimizer="sgd", loss="binary_crossentropy",
                    metrics=["accuracy"], ctx=ctx)
    hist = est.fit(x, y, batch_size=32, epochs=3, shuffle=False,
                   verbose=False)
    ev = est.evaluate(x, y, batch_size=32)
    pred = est.predict(x, batch_size=32)
    return {"losses": [round(v, 6) for v in hist.history["loss"]],
            "accuracy": round(ev["accuracy"], 6),
            "pred_sum": round(float(np.sum(pred)), 5),
            "pred_rows": int(pred.shape[0])}


@pytest.fixture(scope="module")
def runs():
    multi = _run_workers(2)
    single = _single_process_reference()
    return multi, single


def test_two_process_training_matches_single_process(runs):
    multi, ref = runs
    for w in multi:
        np.testing.assert_allclose(w["losses"], ref["losses"],
                                   rtol=1e-5, atol=1e-6)
    assert len(ref["losses"]) == 3


def test_uneven_partitions_do_not_deadlock():
    """n=257 -> partitions of 128/129 rows -> differing local batch counts;
    Estimator._sync_batch_count must pad the short process with weight-0
    batches so the collective step counts match (otherwise the 9th psum on
    one process blocks forever)."""
    outs = _run_workers(2, n_rows=257)
    assert outs[0]["losses"] == outs[1]["losses"]
    assert outs[0]["pred_rows"] + outs[1]["pred_rows"] == 257


def test_two_process_eval_and_predict_consistent(runs):
    multi, ref = runs
    # evaluate() feeds each process's partition -> global metrics, identical
    # on every process and equal to the single-process run
    for w in multi:
        assert abs(w["accuracy"] - ref["accuracy"]) < 1e-5
    # predict() returns each process's local rows; union == full dataset
    assert multi[0]["pred_rows"] + multi[1]["pred_rows"] == ref["pred_rows"]
    total = multi[0]["pred_sum"] + multi[1]["pred_sum"]
    np.testing.assert_allclose(total, ref["pred_sum"], rtol=1e-4)
