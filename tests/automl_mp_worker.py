"""Multi-process AutoML worker (spawned by tests/test_automl_multiprocess.py).

Each process: 2 virtual CPU devices, jax.distributed bootstrap via
ZooConf.coordinator_address, then the context is REBUILT over
jax.local_devices() so every trial trains process-locally (no cross-process
collectives inside trials) — the MultiProcessSearchEngine contract.  Runs an
AutoTS search with distributed=True and prints one JSON line: the per-trial
metrics (identical on every process after the allgather), the best config,
how many trials THIS process executed, and the search wall time.

Run: python tests/automl_mp_worker.py <coordinator> <num_procs> <pid>
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# gloo CPU collectives only for REAL multi-process runs: this jaxlib's
# make_gloo_tcp_collectives binding requires a live DistributedRuntimeClient,
# so requesting gloo in a single-process worker (no jax.distributed
# bootstrap -> client is None) aborts CPU backend init outright
if len(sys.argv) > 2 and int(sys.argv[2]) > 1:
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


N_TRIALS = 4


def make_recipe():
    from analytics_zoo_tpu.automl.regression import Recipe
    from analytics_zoo_tpu.automl.search import Choice

    class _R(Recipe):
        n_trials = N_TRIALS

        def search_space(self, all_available_features=()):
            return {"model": "LSTM", "lstm_units": Choice([4, 8]),
                    "lr": Choice([0.01, 0.003]), "lookback": Choice([8]),
                    "dropout": Choice([0.0]), "epochs": Choice([2]),
                    "batch_size": Choice([32])}
    return _R()


def make_df(n=160):
    import pandas as pd
    g = np.random.default_rng(0)
    return pd.DataFrame({
        "datetime": pd.date_range("2020-01-01", periods=n, freq="h"),
        "value": np.sin(np.arange(n) / 12.0)
        + 0.05 * g.normal(size=n).astype(np.float32)})


def main():
    import time

    coord, nprocs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    from analytics_zoo_tpu.common.context import ZooConf, init_context
    if nprocs > 1:
        init_context(ZooConf(seed=42, coordinator_address=coord,
                             num_processes=nprocs, process_id=pid))
    # trials must be process-local: rebuild the context over local devices
    init_context(devices=jax.local_devices(), seed=42)

    from analytics_zoo_tpu.automl.regression import TimeSequencePredictor

    pred = TimeSequencePredictor(future_seq_len=1, recipe=make_recipe(),
                                 distributed=True)
    df = make_df()

    # count trials executed on THIS process: _train_one runs once per local
    # trial plus once for the best-config retrain
    calls = []
    orig_train_one = TimeSequencePredictor._train_one

    def counting(self, cfg, df_):
        calls.append(1)
        return orig_train_one(self, cfg, df_)

    TimeSequencePredictor._train_one = counting
    t0 = time.time()
    pipe = pred.fit(df, verbose=False)
    dt = time.time() - t0
    TimeSequencePredictor._train_one = orig_train_one
    engine_trials = [(t.config["lstm_units"], t.config["lr"],
                      round(t.metric, 6)) for t in pred._last_trials]
    print(json.dumps({
        "pid": pid,
        "trials": engine_trials,
        "best": {k: pipe.config[k] for k in ("lstm_units", "lr")},
        "local_trial_count": len(calls) - 1,   # minus the best retrain
        "search_seconds": round(dt, 2),
    }))


if __name__ == "__main__":
    main()
